"""Nemesis FaultPlan: one seed-deterministic fault vocabulary, two backends.

The subsystem's contract (madsim_tpu/nemesis.py):
  * schedule-level clauses (crash/wipe/partition/clog/spike/skew) fire at
    times that are pure functions of (seed, occurrence index) — the pure
    `FaultPlan.schedule` IS the stream both backends execute;
  * message-level clauses (loss/dup/reorder) are per-backend coin streams
    whose FIRE COUNTS surface in the chaos-coverage report;
  * every clause firing is counted, so a dead clause is visible.

`chaos`-marked tests are the fast smoke tier (`make chaos-smoke`);
`slow`-marked sweeps are the 1024-seed acceptance runs.
"""

import dataclasses

import pytest

from madsim_tpu import nemesis
from madsim_tpu.nemesis import (
    ClockSkew,
    Crash,
    Duplicate,
    FaultPlan,
    LatencySpike,
    LinkClog,
    MsgLoss,
    Partition,
    Reorder,
)

HORIZON_US = 4_000_000

FULL_PLAN = FaultPlan(
    name="full",
    clauses=(
        Crash(interval_lo_us=400_000, interval_hi_us=1_500_000,
              down_lo_us=300_000, down_hi_us=1_000_000, wipe_rate=0.3),
        Partition(interval_lo_us=500_000, interval_hi_us=2_000_000,
                  heal_lo_us=400_000, heal_hi_us=1_500_000),
        LinkClog(interval_lo_us=600_000, interval_hi_us=2_000_000),
        LatencySpike(interval_lo_us=700_000, interval_hi_us=2_500_000,
                     extra_us=50_000),
        MsgLoss(rate=0.02),
        Duplicate(rate=0.05),
        Reorder(rate=0.1, window_us=40_000),
        ClockSkew(max_ppm=50_000),
    ),
)

# the acceptance-criteria composition: crash + partition + duplication +
# reorder + clock skew, one plan, both backends, one seed
ACCEPT_PLAN = FaultPlan(
    name="acceptance",
    clauses=(
        Crash(interval_lo_us=400_000, interval_hi_us=1_500_000,
              down_lo_us=300_000, down_hi_us=1_000_000),
        Partition(interval_lo_us=500_000, interval_hi_us=2_000_000,
                  heal_lo_us=400_000, heal_hi_us=1_500_000),
        Duplicate(rate=0.05),
        Reorder(rate=0.1, window_us=40_000),
        ClockSkew(max_ppm=20_000),
    ),
)


# ------------------------------------------------------------------ pure


def test_schedule_is_pure_and_seed_sensitive():
    a = FULL_PLAN.schedule(7, HORIZON_US, 5)
    b = FULL_PLAN.schedule(7, HORIZON_US, 5)
    c = FULL_PLAN.schedule(8, HORIZON_US, 5)
    assert a == b
    assert a != c
    assert all(0 <= e.t_us < HORIZON_US or e.kind == "skew" for e in a)
    kinds = {e.kind for e in a}
    assert {"crash", "restart", "split", "clog", "spike_on", "skew"} <= kinds
    # crash/restart alternate per victim stream and times are monotone
    times = [e.t_us for e in a]
    assert times == sorted(times)


def test_schedule_respects_horizon_and_node_count():
    for seed in range(16):
        for e in FULL_PLAN.schedule(seed, 1_000_000, 3):
            assert e.t_us < 1_000_000
            if e.kind in ("crash", "restart"):
                assert 0 <= e.node < 3
            if e.kind in ("clog", "unclog"):
                assert 0 <= e.node < 3 and 0 <= e.dst < 3
                assert e.node != e.dst  # a link, not a loopback
            if e.kind == "split":
                assert 0 <= e.side_mask < 8


def test_skew_assignment_pure_and_bounded():
    ppm = FULL_PLAN.skew_ppm(3, 5)
    assert ppm == FULL_PLAN.skew_ppm(3, 5)
    assert len(ppm) == 5
    assert all(-50_000 <= p <= 50_000 for p in ppm)
    assert FULL_PLAN.skew_ppm(4, 5) != ppm


def test_plan_validation_rejects_bad_clauses():
    with pytest.raises(ValueError, match="must be in \\[0, 1\\)"):
        FaultPlan(clauses=(MsgLoss(rate=1.5),))
    with pytest.raises(ValueError, match="must be in \\[0, 1\\)"):
        FaultPlan(clauses=(Duplicate(rate=-0.1),))
    with pytest.raises(ValueError, match="interval"):
        FaultPlan(clauses=(Crash(interval_lo_us=10, interval_hi_us=5),))
    with pytest.raises(ValueError, match="window_us"):
        FaultPlan(clauses=(Reorder(rate=0.1, window_us=0),))
    with pytest.raises(ValueError, match="duplicate"):
        FaultPlan(clauses=(MsgLoss(), MsgLoss()))
    with pytest.raises(TypeError):
        FaultPlan(clauses=("not-a-clause",))


def test_prng_mirror_matches_device_prng():
    """The pure-Python murmur3 chain must be bit-exact against tpu/prng —
    it is the load-bearing wall of cross-backend schedule agreement."""
    jnp = pytest.importorskip("jax.numpy")
    from madsim_tpu.tpu import prng

    for seed in (0, 1, 0xDEADBEEF, 2**32 - 1):
        key_py = nemesis.key_from_seed(seed)
        key_dev = int(prng.key_from(jnp.uint32(seed)))
        assert key_py == key_dev
        for site in (1, 201, 241):
            for idx in (0, 1, 63, 10_000):
                assert nemesis.bits32(key_py, site, idx) == int(
                    prng.bits(jnp.uint32(key_dev), site, index=jnp.uint32(idx))
                )
                assert nemesis.randint32(key_py, site, -50, 700, idx) == int(
                    prng.randint(
                        jnp.uint32(key_dev), site, -50, 700,
                        index=jnp.uint32(idx),
                    )
                )


# ------------------------------------------------------------------ config


def test_netconfig_validates_like_the_engine():
    from madsim_tpu.core.config import Config, NetConfig

    with pytest.raises(ValueError, match="packet_loss_rate must be in \\[0, 1\\), got 1.5"):
        NetConfig(packet_loss_rate=1.5)
    with pytest.raises(ValueError, match="packet_loss_rate must be in \\[0, 1\\), got -0.1"):
        Config.parse("[net]\npacket_loss_rate = -0.1\n")
    with pytest.raises(ValueError, match="packet_duplicate_rate"):
        Config.parse("[net]\npacket_duplicate_rate = 2.0\n")
    with pytest.raises(ValueError, match="0 <= lo <= hi"):
        NetConfig(send_latency_min=0.1, send_latency_max=0.05)
    # a reorder rate with no window would silently run zero reordering —
    # same contract as the engine's nem_reorder validation
    with pytest.raises(ValueError, match="packet_reorder_window > 0"):
        NetConfig(packet_reorder_rate=0.5)


def test_config_hash_keys_on_nemesis_knobs():
    from madsim_tpu.core.config import Config

    base = Config()
    toml = base.to_toml()
    for knob in (
        "packet_extra_loss_rate", "packet_duplicate_rate",
        "packet_reorder_rate", "packet_reorder_window",
    ):
        assert knob in toml, f"{knob} missing from to_toml"
    tweaked = Config()
    tweaked.net.packet_duplicate_rate = 0.07
    assert tweaked.hash() != base.hash()
    # and the knobs round-trip through parse
    again = Config.parse(tweaked.to_toml())
    assert again.net.packet_duplicate_rate == 0.07
    assert again.hash() == tweaked.hash()


def test_fault_plan_to_net_config():
    net = FULL_PLAN.to_net_config()
    assert net.packet_extra_loss_rate == 0.02
    assert net.packet_duplicate_rate == 0.05
    assert net.packet_reorder_rate == 0.1
    assert net.packet_reorder_window == pytest.approx(0.04)


# ------------------------------------------------------------------ buggify


def test_buggify_two_level_semantics():
    import madsim_tpu as ms

    def run(seed, hits=400):
        rt = ms.Runtime(seed=seed)

        async def body():
            ms.buggify.enable()
            fired = sum(
                1 for _ in range(hits) if ms.buggify.buggify("slow_disk")
            )
            active = ms.buggify.is_active("slow_disk")
            return active, fired, ms.buggify.fire_counts()

        return rt.block_on(body())

    results = {seed: run(seed) for seed in range(24)}
    # determinism: same seed => same activation AND same fire count
    for seed, (active, fired, counts) in results.items():
        assert run(seed) == (active, fired, counts)
        if active:
            # an active point at p=0.25 over 400 hits essentially must fire
            assert fired > 0
            assert counts == {"slow_disk": fired}
        else:
            assert fired == 0
            assert counts == {}
    # two-level: SOME runs activate the point, some don't (0.25 each way
    # over 24 seeds: both outcomes all-but-certain)
    actives = [a for a, _, _ in results.values()]
    assert any(actives) and not all(actives)


def test_buggify_activation_is_call_order_independent():
    import madsim_tpu as ms

    def run(order):
        rt = ms.Runtime(seed=11)

        async def body():
            ms.buggify.enable()
            return {n: ms.buggify.is_active(n) for n in order}

        return rt.block_on(body())

    names = ["a", "b", "slow_disk", "partition_heal"]
    assert run(names) == run(list(reversed(names)))


def test_unnamed_buggify_unchanged():
    import madsim_tpu as ms

    rt = ms.Runtime(seed=5)

    async def body():
        assert not ms.buggify.buggify()  # disabled by default
        ms.buggify.enable()
        fired = sum(1 for _ in range(400) if ms.buggify.buggify())
        return fired

    fired = rt.block_on(body())
    assert 40 < fired < 160  # ~25%
    # unnamed points are not in the named registry
    assert rt.handle.rng.buggify_fires == {}


# ------------------------------------------------------------------ device

jnp = None


def _dev():
    global jnp
    import jax.numpy as _j

    jnp = _j
    from madsim_tpu.tpu import BatchedSim, SimConfig, make_raft_spec, summarize
    from madsim_tpu.tpu import nemesis as tpu_nemesis

    return BatchedSim, SimConfig, make_raft_spec, summarize, tpu_nemesis


@pytest.mark.chaos
def test_device_chaos_stream_equals_pure_schedule():
    """The engine executes EXACTLY the plan's pure schedule: times, kinds,
    victims, partition sides, clog pairs — for several seeds.

    (Wipe-free variant of the full plan: wiping Raft's durable state
    legitimately violates its invariants, and a frozen violating lane
    truncates its chaos stream early — a different, correct behavior.)"""
    BatchedSim, SimConfig, make_raft_spec, _, tn = _dev()
    plan = FaultPlan(
        name="stream",
        clauses=tuple(
            dataclasses.replace(c, wipe_rate=0.0) if isinstance(c, Crash) else c
            for c in FULL_PLAN.clauses
        ),
    )
    cfg = tn.compile_plan(plan, SimConfig(horizon_us=HORIZON_US))
    sim = BatchedSim(make_raft_spec(5), cfg)
    total = 0
    for seed in (0, 1, 7, 1234):
        total += tn.assert_device_matches_schedule(
            sim, plan, seed, horizon_us=HORIZON_US
        )
    assert total > 20  # the comparison actually compared things


@pytest.mark.chaos
def test_acceptance_plan_both_paths_bit_identical_and_all_clauses_fire():
    """The acceptance composition (crash + partition + duplication +
    reorder + clock skew) on a 64-lane smoke: bit-identical repeat runs
    (check_determinism) and nonzero fire counts for every enabled clause."""
    from madsim_tpu.tpu.batch import run_batch
    from madsim_tpu.tpu.raft import raft_workload

    BatchedSim, SimConfig, make_raft_spec, _, tn = _dev()
    wl = raft_workload(virtual_secs=HORIZON_US / 1e6)
    wl = dataclasses.replace(
        wl, config=tn.compile_plan(ACCEPT_PLAN, wl.config), host_repro=None
    )
    res = run_batch(
        range(64), wl, repro_on_host=False, max_traces=0,
        check_determinism=True,
    )
    assert res.violations == 0, res.summary
    for kind in ACCEPT_PLAN.enabled_kinds:
        assert res.chaos_fires.get(kind, 0) > 0, (kind, res.chaos_fires)
    assert "DEAD CLAUSE" not in res.chaos_report()
    assert "crash" in res.chaos_report()


@pytest.mark.chaos
def test_dead_clause_visible_in_coverage_report():
    """A clause whose knobs can never fire inside the horizon must show up
    as a dead clause, not silently report chaos it never ran."""
    from madsim_tpu.tpu.batch import run_batch
    from madsim_tpu.tpu.raft import raft_workload

    BatchedSim, SimConfig, make_raft_spec, _, tn = _dev()
    dead = FaultPlan(clauses=(
        Crash(interval_lo_us=400_000, interval_hi_us=1_500_000,
              down_lo_us=300_000, down_hi_us=1_000_000),
        # first split can never arrive before the horizon => dead clause
        Partition(interval_lo_us=50_000_000, interval_hi_us=60_000_000),
    ))
    wl = raft_workload(virtual_secs=2.0)
    wl = dataclasses.replace(
        wl, config=tn.compile_plan(dead, wl.config), host_repro=None
    )
    res = run_batch(range(16), wl, repro_on_host=False, max_traces=0)
    assert res.chaos_fires["crash"] > 0
    assert res.chaos_fires["partition"] == 0
    assert "DEAD CLAUSE" in res.chaos_report()
    assert "partition" in res.chaos_report().split("DEAD CLAUSE")[1]


@pytest.mark.chaos
def test_dead_node_drops_counted_separately_from_overflow():
    """engine satellite: sends to crashed nodes land in `dead_drops`, not
    `overflow` — pool pressure and crash fallout are different diagnoses.
    Differential: same seeds without the crash clause count ZERO dead
    drops, so the counter isolates crash fallout exactly."""
    from madsim_tpu.tpu.raft import raft_workload

    BatchedSim, SimConfig, make_raft_spec, summarize, tn = _dev()
    wl = raft_workload(virtual_secs=3.0)
    base = dataclasses.replace(
        wl.config, crash_interval_lo_us=0, crash_interval_hi_us=0,
        partition_interval_lo_us=0, partition_interval_hi_us=0,
        loss_rate=0.0,
    )
    plan = FaultPlan(clauses=(
        Crash(interval_lo_us=200_000, interval_hi_us=800_000,
              down_lo_us=500_000, down_hi_us=2_000_000),
    ))
    crashy = BatchedSim(wl.spec, tn.compile_plan(plan, base))
    s = summarize(crashy.run(jnp.arange(32), max_steps=30_000))
    # long downtimes + heartbeats at dead nodes: dead drops must be seen
    assert s["total_dead_drops"] > 0
    assert s["violations"] == 0
    quiet = BatchedSim(wl.spec, base)
    sq = summarize(quiet.run(jnp.arange(32), max_steps=30_000))
    assert sq["total_dead_drops"] == 0
    assert sq["violations"] == 0


@pytest.mark.chaos
def test_clock_skew_perturbs_trajectories_but_stays_safe():
    """Skew must actually CHANGE behavior (different event counts vs the
    unskewed run of the same seeds) while every safety invariant holds."""
    import numpy as np

    BatchedSim, SimConfig, make_raft_spec, summarize, tn = _dev()
    base_cfg = SimConfig(horizon_us=3_000_000)
    plain = BatchedSim(make_raft_spec(5), base_cfg).run(
        jnp.arange(32), max_steps=30_000
    )
    skew_cfg = tn.compile_plan(
        FaultPlan(clauses=(ClockSkew(max_ppm=100_000),)), base_cfg
    )
    skewed = BatchedSim(make_raft_spec(5), skew_cfg).run(
        jnp.arange(32), max_steps=30_000
    )
    assert summarize(plain)["violations"] == 0
    assert summarize(skewed)["violations"] == 0
    ev_a = np.asarray(plain.events)
    ev_b = np.asarray(skewed.events)
    assert (ev_a != ev_b).any(), "10% clock skew changed nothing"


def test_skew_integer_ppm_exact_long_horizon():
    """The r8 precision fix (ISSUE 6): timer skew is exact integer ppm
    math for EVERY i32 microsecond delay. The r1-r7 path cast through
    `float32 * rate`, whose 24-bit mantissa quantizes delays above
    2^24 us (~16.7 virtual seconds) to multiples of 2, 4, 8... — a
    20-minute soak timer lost up to ~64 us per arming, silently, per
    node. scale_delay_ppm must agree with arbitrary-precision Python int
    truncation everywhere; the old formula provably does NOT."""
    import numpy as np
    import jax.numpy as jnp

    from madsim_tpu.tpu.engine import scale_delay_ppm

    rng = np.random.default_rng(8)
    # the long-horizon band is the regression: delays well past 2^24 us,
    # up to the i32 ceiling, plus the boundary and small-delay bands
    delays = np.concatenate([
        rng.integers(0, 1 << 24, 200),
        np.asarray([(1 << 24) - 1, 1 << 24, (1 << 24) + 1]),
        rng.integers(1 << 24, 2**31 - 1, 400),
        np.asarray([2**31 - 1, 0, 1]),
    ]).astype(np.int64)
    ppms = np.concatenate([
        rng.integers(-999_999, 1_000_000, 20),
        np.asarray([0, 1, -1, 999_999, -999_999, 250_000]),
    ]).astype(np.int64)

    def exact(d, ppm):  # arbitrary-precision ground truth
        adj = int(d) * abs(int(ppm)) // 1_000_000
        return int(d) + adj if ppm >= 0 else int(d) - adj

    for ppm in ppms:
        # guard: the adjusted delay must stay in i32 for the comparison
        ds = delays[np.asarray(
            [abs(exact(d, ppm)) < 2**31 for d in delays]
        )]
        got = np.asarray(
            scale_delay_ppm(jnp.asarray(ds, jnp.int32), jnp.int32(ppm)),
            np.int64,
        )
        want = np.asarray([exact(d, ppm) for d in ds], np.int64)
        np.testing.assert_array_equal(got, want, err_msg=f"ppm={ppm}")
    # the host mirror (core/vtime.skew_delay_ns) applies the same
    # truncation RULE in Python ints (the `exact` expression), but at ns
    # granularity vs the device's us — a given delay's stretch can still
    # differ by up to 1 us between faces, so this is a shared-spec
    # exactness guarantee, NOT cross-face timer bit-equality (the twin
    # suite compares skew assignments, never event times).

    # ...and the OLD f32 path fails this long-horizon band: above 2^24 us
    # the float mantissa cannot represent every integer microsecond
    big = np.arange((1 << 25), (1 << 25) + 64, dtype=np.int64)
    ppm = 1
    old = (big.astype(np.float32) * np.float32(1.0 + ppm * 1e-6)).astype(
        np.int64
    )
    new = np.asarray([exact(d, ppm) for d in big], np.int64)
    assert (old != new).any(), (
        "the f32 skew path is suddenly exact above 2^24 us — if float64 "
        "crept in, the device/host bit-identity argument changed; revisit"
    )


@pytest.mark.chaos
def test_duplication_delivers_more_events_than_it_sends():
    """With a heavy dup rate, delivered-event counts must rise against the
    same seeds without duplication (the copies really arrive)."""
    import numpy as np

    BatchedSim, SimConfig, make_raft_spec, summarize, tn = _dev()
    base_cfg = SimConfig(horizon_us=2_000_000)
    plain = BatchedSim(make_raft_spec(5), base_cfg).run(
        jnp.arange(24), max_steps=30_000
    )
    dup_cfg = tn.compile_plan(
        FaultPlan(clauses=(Duplicate(rate=0.3),)), base_cfg
    )
    dupped = BatchedSim(make_raft_spec(5), dup_cfg).run(
        jnp.arange(24), max_steps=30_000
    )
    assert summarize(plain)["violations"] == 0
    assert summarize(dupped)["violations"] == 0
    assert (
        np.asarray(dupped.events).sum() > np.asarray(plain.events).sum()
    )
    assert int(np.asarray(dupped.fires).sum(0)[
        nemesis.FIRE_INDEX["dup"]
    ]) > 0


@pytest.mark.chaos
def test_engine_rejects_legacy_plus_nemesis_combo():
    BatchedSim, SimConfig, make_raft_spec, _, tn = _dev()
    cfg = tn.compile_plan(
        FaultPlan(clauses=(Crash(),)), SimConfig()
    )
    cfg = dataclasses.replace(
        cfg, crash_interval_lo_us=1_000_000, crash_interval_hi_us=2_000_000
    )
    with pytest.raises(ValueError, match="cannot both be enabled"):
        BatchedSim(make_raft_spec(5), cfg)
    with pytest.raises(ValueError, match="nem_dup_rate must be in"):
        BatchedSim(make_raft_spec(5), SimConfig(nem_dup_rate=1.5))


@pytest.mark.chaos
@pytest.mark.slow
def test_acceptance_1024_seed_batch_reports_every_clause():
    """The acceptance sweep: 1024 seeds, full acceptance plan, nonzero
    fire counts for EVERY enabled clause in BatchResult."""
    from madsim_tpu.tpu.batch import run_batch
    from madsim_tpu.tpu.raft import raft_workload

    _, _, _, _, tn = _dev()
    wl = raft_workload(virtual_secs=3.0)
    wl = dataclasses.replace(
        wl, config=tn.compile_plan(ACCEPT_PLAN, wl.config), host_repro=None
    )
    res = run_batch(range(1024), wl, repro_on_host=False, max_traces=0)
    assert res.violations == 0, res.summary
    assert res.summary["lanes"] == 1024
    for kind in ACCEPT_PLAN.enabled_kinds:
        assert res.chaos_fires.get(kind, 0) > 0, (kind, res.chaos_fires)
    assert "DEAD CLAUSE" not in res.chaos_report()


@pytest.mark.chaos
def test_reconfig_join_wipes_fs_no_inode_resurrection():
    """create -> remove -> rejoin -> stat, end to end through the driver:
    a node that wrote and SYNCED a file before its reconfig removal must
    come back with a blank disk (FsSim.wipe_node runs before the join's
    restart) — synced durability is a crash promise, not a membership
    one. Nodes the plan never removed keep their files."""
    import madsim_tpu as ms
    from madsim_tpu import fs
    from madsim_tpu.nemesis import Reconfig

    N, SEED, HOR_US = 5, 5, 4_000_000
    plan = FaultPlan(name="join-wipe", clauses=(
        Reconfig(interval_lo_us=500_000, interval_hi_us=1_200_000,
                 down_lo_us=200_000, down_hi_us=600_000),
    ))
    joined = sorted(
        {e.node for e in plan.schedule(SEED, HOR_US, N) if e.kind == "join"}
    )
    assert joined, "pick a seed whose plan completes a remove -> join"
    incarnations = [0] * N

    async def body():
        handle = ms.Handle.current()

        def mk(i):
            async def run():
                # only the FIRST incarnation writes its marker; a rejoin
                # must not find it
                if incarnations[i] == 0:
                    f = await fs.File.create("/data/marker")
                    await f.write_all_at(b"pre-removal", 0)
                    await f.sync_all()
                incarnations[i] += 1
                while True:
                    await ms.time.sleep(0.05)

            return run

        nodes = [
            handle.create_node().name(f"fsn-{i}").ip(f"10.0.5.{i + 1}")
            .init(mk(i)).build()
            for i in range(N)
        ]
        driver = nemesis.NemesisDriver(
            plan, handle, [nd.id for nd in nodes], horizon_us=HOR_US,
        )
        driver.install()
        t = ms.time.current()
        end = t.elapsed() + HOR_US / 1e6
        while t.elapsed() < end:
            await ms.time.sleep(0.02)
        sim = ms.plugin.simulator(fs.FsSim)
        return driver, [sim.get_file_size(nd.id, "/data/marker")
                        for nd in nodes]

    rt = ms.Runtime(seed=SEED)
    driver, sizes = rt.block_on(body())
    got_joined = sorted({e.node for e in driver.applied if e.kind == "join"})
    assert got_joined == joined
    for i in range(N):
        if i in joined:
            assert sizes[i] is None, (
                f"node {i} rejoined with its pre-removal inode intact"
            )
            assert incarnations[i] >= 2
        else:
            assert sizes[i] == len(b"pre-removal")
