"""Autotune (madsim_tpu/tune) + the codified measurement discipline
(madsim_tpu/measure).

The subsystem's contract (docs/tuning.md):
  * Tier-A dispatch knobs are RESULT-INVARIANT: per-seed rows are
    bit-identical across chunk width, segment length, pipeline mode,
    refill lane width — the matrix that lets `tuning="auto"` apply
    anywhere, even mid-campaign;
  * the tuned-config cache (`madsim-tpu-tuned/1`) round-trips exactly,
    and rejects stale formats / wrong-device entries LOUDLY instead of
    half-applying them;
  * the Tier-B gate refuses a drop-inducing pool config next to its
    clean twin (overflow == 0 is non-negotiable for cached configs);
  * campaigns persist the resolved tuning and reject a resume under a
    different tuned cache (the r10 silently-dropped-mesh bug class);
  * the measurement discipline warms the EXACT timed program and derives
    fresh seeds per rep — the node_sharding warmed-with-a-different-
    step-count compile-timing bug (perf_notes §1-D) as a regression
    test instead of a footnote.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from madsim_tpu import measure, tune


def _raft_workload(virtual_secs: float = 0.5):
    from madsim_tpu.tpu import raft_workload

    return dataclasses.replace(
        raft_workload(virtual_secs=virtual_secs), host_repro=None
    )


# ---------------------------------------------------------- the discipline


def test_fresh_seeds_are_disjoint_per_rep():
    a, b = measure.fresh_seeds(0, 8), measure.fresh_seeds(1, 8)
    assert a.dtype == np.uint32 and b.dtype == np.uint32
    assert not set(a.tolist()) & set(b.tolist())
    assert measure.median([3.0, 1.0, 2.0]) == 2.0
    with pytest.raises(ValueError):
        measure.fresh_seeds(0, 0)


def test_time_scan_ms_warms_the_exact_timed_program():
    """THE node_sharding regression (perf_notes §1-D caveat): run_steps
    jits per (shape, n_steps), so the warmup must run the exact
    (shape, scan) program before any timed rep — and every timed rep
    must init from a FRESH seed block (the relay caches identical
    dispatches)."""
    calls = []

    def init(seeds):
        calls.append(("init", int(seeds[0])))
        return "st"

    def run_steps(st, n):
        calls.append(("run", int(n)))
        return st

    measure.time_scan_ms(
        init, run_steps, lanes=4, scan=60, warm_steps=10, rounds=2,
        block=lambda x: None,
    )
    runs = [n for kind, n in calls if kind == "run"]
    inits = [s for kind, s in calls if kind == "init"]
    # the timed (shape, 60) program ran during the warm phase — before
    # the first timed rep's init
    first_timed_init = calls.index(("init", inits[1]))
    assert ("run", 60) in calls[:first_timed_init], (
        "warmup never ran the exact timed (shape, scan) program — the "
        "first timed rep would contain its XLA compile"
    )
    # warmup + 2 reps, each running warm_steps then scan
    assert runs == [10, 60, 10, 60, 10, 60]
    # fresh seeds per rep: three distinct seed blocks (warm, rep1, rep2)
    assert len(set(inits)) == 3


def test_sweep_timer_warms_once_per_compile_key():
    log = []

    def run(assign, rep):
        log.append((assign["k"], rep))
        return None

    timer = measure.SweepTimer(
        run, compile_key=lambda a: a["k"], block=lambda x: None
    )
    timer({"k": 1}, rep=1)
    timer({"k": 1}, rep=2)
    timer({"k": 2}, rep=3)
    # key 1 warmed once (rep 0), key 2 warmed once; timed reps untouched
    assert log == [(1, 0), (1, 1), (1, 2), (2, 0), (2, 3)]


def test_interleaved_medians_interleaves_and_advances_reps():
    seen = []
    meds = measure.interleaved_medians(
        {"a": lambda r: seen.append(("a", r)),
         "b": lambda r: seen.append(("b", r))},
        rounds=2, block=lambda x: None,
    )
    assert [s[0] for s in seen] == ["a", "b", "a", "b"]
    assert len({r for _, r in seen}) == 4  # globally unique rep indices
    assert set(meds) == {"a", "b"}


# ------------------------------------------------------------ cache + keys


def test_lane_bucket_and_config_hash_sans_tier_b():
    from madsim_tpu.tpu.spec import SimConfig

    assert tune.lane_bucket(1) == 1
    assert tune.lane_bucket(300) == 512
    assert tune.lane_bucket(4096) == 4096
    cfg = SimConfig()
    tuned = dataclasses.replace(
        cfg, msg_capacity=256, msg_depth_msg=3, msg_depth_timer=2,
        msg_spare_slots=4,
    )
    # the key is STABLE under the very knobs Tier B changes...
    assert tune.config_hash_sans_tier_b(cfg) == \
        tune.config_hash_sans_tier_b(tuned)
    # ...and sensitive to everything else
    assert tune.config_hash_sans_tier_b(cfg) != \
        tune.config_hash_sans_tier_b(
            dataclasses.replace(cfg, horizon_us=1)
        )
    # Tier-B values DO move the full config hash (resume-conflict guard)
    assert cfg.hash() != tuned.hash()


def test_tuned_cache_roundtrip_and_miss(tmp_path):
    from madsim_tpu.tpu.spec import SimConfig

    cfg = SimConfig()
    entry = tune.TunedEntry(
        device_kind=tune.device_kind(), workload="raft",
        config_hash=tune.config_hash_sans_tier_b(cfg),
        lane_bucket=tune.lane_bucket(40),
        dispatch={"chunk": 32, "pipeline": False},
        baseline_seeds_per_sec=10.0, tuned_seeds_per_sec=12.0, trials=5,
    )
    path = entry.save(str(tmp_path))
    assert os.path.exists(path)
    again = tune.load_tuned("raft", cfg, 40, dir=str(tmp_path))
    assert again == entry
    # lane bucket 33..64 all resolve to the same entry
    assert tune.load_tuned("raft", cfg, 64, dir=str(tmp_path)) == entry
    # clean misses: other bucket, other workload, other config
    assert tune.load_tuned("raft", cfg, 128, dir=str(tmp_path)) is None
    assert tune.load_tuned("kv", cfg, 40, dir=str(tmp_path)) is None
    other = dataclasses.replace(cfg, horizon_us=123_456)
    assert tune.load_tuned("raft", other, 40, dir=str(tmp_path)) is None
    # resolve_tuning("auto") consumes the hit and survives the miss
    assert tune.resolve_tuning(
        "auto", "raft", cfg, 40, dir=str(tmp_path)
    ) == {"chunk": 32, "pipeline": False}
    assert tune.resolve_tuning(
        "auto", "raft", cfg, 128, dir=str(tmp_path)
    ) == {}


def test_tuned_cache_rejects_stale_format_and_wrong_device(tmp_path):
    from madsim_tpu.tpu.spec import SimConfig

    cfg = SimConfig()
    entry = tune.TunedEntry(
        device_kind=tune.device_kind(), workload="raft",
        config_hash=tune.config_hash_sans_tier_b(cfg),
        lane_bucket=tune.lane_bucket(40),
    )
    path = entry.save(str(tmp_path))

    def rewrite(**patch):
        doc = entry.to_doc()
        doc.update(patch)
        with open(path, "w") as f:
            json.dump(doc, f)

    # stale format version: loud reject, never silently reinterpreted
    rewrite(format="madsim-tpu-tuned/0")
    with pytest.raises(tune.TunedCacheError, match="format"):
        tune.load_tuned("raft", cfg, 40, dir=str(tmp_path))
    # wrong device_kind at the right key path (a cache copied from
    # another machine): loud reject
    rewrite(device_kind="TPU_v99")
    with pytest.raises(tune.TunedCacheError, match="does not match"):
        tune.load_tuned("raft", cfg, 40, dir=str(tmp_path))
    # unknown fields (written by a newer tree): loud reject
    rewrite(frobnicate=1)
    with pytest.raises(tune.TunedCacheError, match="unknown"):
        tune.load_tuned("raft", cfg, 40, dir=str(tmp_path))
    # a Tier-B knob smuggled into the dispatch dict: loud reject
    rewrite(dispatch={"msg_capacity": 8})
    with pytest.raises(tune.TunedCacheError, match="non-Tier-A"):
        tune.load_tuned("raft", cfg, 40, dir=str(tmp_path))


def test_resolve_tuning_forms():
    from madsim_tpu.tpu.spec import SimConfig

    cfg = SimConfig()
    assert tune.resolve_tuning(None, "raft", cfg, 64) == {}
    assert tune.resolve_tuning({"chunk": 8}, "raft", cfg, 64) == {"chunk": 8}
    with pytest.raises(ValueError, match="not Tier-A"):
        tune.resolve_tuning({"msg_capacity": 8}, "raft", cfg, 64)
    with pytest.raises(TypeError):
        tune.resolve_tuning(3.14, "raft", cfg, 64)


# -------------------------------------------------- Tier-A invariance matrix


@pytest.mark.chaos
def test_tier_a_invariance_matrix_run_batch():
    """Tuned dispatch knobs vs defaults on the chunked, pipelined,
    refill and sharded paths: per-seed rows bit-identical — the contract
    that makes Tier A safe to apply anywhere."""
    from madsim_tpu.tpu.batch import run_batch

    wl = _raft_workload()
    base = run_batch(range(48), wl, mesh=None, max_traces=0)
    for tuning in (
        {"chunk": 16, "pipeline": False},
        {"dispatch_steps": 200},
        {"refill_lanes": 8},
        {"chunk": 12, "dispatch_steps": 500, "refill_lanes": 4,
         "pipeline": False},
    ):
        got = run_batch(
            range(48), wl, mesh=None, max_traces=0, tuning=tuning
        )
        assert np.array_equal(base.violated, got.violated), tuning
        assert np.array_equal(base.deadlocked, got.deadlocked), tuning
        assert np.array_equal(
            base.violation_step, got.violation_step
        ), tuning
    # sharded legs (the suite conftest forces an 8-device CPU mesh): a
    # tuned `devices` entry must not move a row either, chunked and
    # refill paths both — mesh omitted so the tuned mesh actually lands
    for tuning in ({"devices": 2}, {"devices": 2, "refill_lanes": 8}):
        got = run_batch(range(48), wl, max_traces=0, tuning=tuning)
        assert got.summary.get("n_devices") == 2, tuning
        assert np.array_equal(base.violated, got.violated), tuning
        assert np.array_equal(base.deadlocked, got.deadlocked), tuning
        assert np.array_equal(
            base.violation_step, got.violation_step
        ), tuning


@pytest.mark.chaos
def test_tier_a_invariance_matrix_spread_mix():
    """The refill engine's own matrix on the 10x horizon-spread mix:
    lane width x segment length never moves a per-admission row."""
    from madsim_tpu.tpu.engine import refill_results

    sim, horizon = tune.spread_mix_sim(0.3)
    A = 24
    ctl = tune.spread_ctl_rows(horizon, A)
    seeds = np.arange(A, dtype=np.uint32)
    rows = []
    for lanes, dsteps in ((4, 10_000), (8, 10_000), (4, 64), (12, 500)):
        st = sim.run_refill(
            seeds, lanes=lanes, max_steps=20_000, dispatch_steps=dsteps,
            ctl=ctl,
        )
        res = refill_results(st)
        rows.append({
            k: np.asarray(res[k])
            for k in ("violated", "steps", "violation_step", "events")
        })
    for other in rows[1:]:
        for k, v in rows[0].items():
            assert np.array_equal(v, other[k]), k


def test_run_batch_rejects_mismatched_prebuilt_sim():
    """run_batch(sim=...) amortizes compiles for the SAME program only: a
    sim built for another (spec, config) would fuzz a different program
    under this workload's name — loud reject, never silent."""
    from madsim_tpu.tpu.batch import run_batch
    from madsim_tpu.tpu.engine import BatchedSim

    wl = _raft_workload()
    other_cfg = dataclasses.replace(wl.config, horizon_us=123_456)
    sim = BatchedSim(wl.spec, other_cfg)
    with pytest.raises(ValueError, match="different"):
        run_batch(range(8), wl, mesh=None, max_traces=0, sim=sim)


def test_run_batch_tuning_applies_and_explicit_args_win():
    from madsim_tpu.tpu.batch import run_batch

    wl = _raft_workload()
    tuned = run_batch(
        range(24), wl, mesh=None, max_traces=0,
        tuning={"refill_lanes": 8},
    )
    assert tuned.summary.get("refill_lanes") == 8
    # an explicit refill= beats the tuned value
    explicit = run_batch(
        range(24), wl, mesh=None, max_traces=0, refill=4,
        tuning={"refill_lanes": 8},
    )
    assert explicit.summary.get("refill_lanes") == 4
    # an explicit refill=0 pins the CHUNKED path (and its summary
    # schema) even when the cache holds a refill width — refill's
    # sentinel is None-omitted, so 0 is an explicit argument like any
    # other and the tuned value must not flip the path
    chunked = run_batch(
        range(24), wl, mesh=None, max_traces=0, refill=0,
        tuning={"refill_lanes": 8},
    )
    assert "refill_lanes" not in chunked.summary


def test_run_batch_cached_devices_beyond_host_falls_back():
    """A tuned entry recorded on a bigger host of the same device kind
    (the cache is keyed by KIND, not count) may name more devices than
    this host has. Applying it must degrade to the production default
    mesh — a cache entry is a throughput decision, never a crash."""
    import jax

    from madsim_tpu.tpu.batch import run_batch

    wl = _raft_workload()
    too_many = len(jax.devices()) + 7
    res = run_batch(
        range(16), wl, max_traces=0, tuning={"devices": too_many}
    )
    assert res.seeds.size == 16
    # the tuner's own search keeps the loud reject: there a bad count
    # is a caller bug, not a stale cache
    with pytest.raises(ValueError, match="visible"):
        tune._mesh_for(too_many)
    assert tune._mesh_for(too_many, cached=True) == "auto"


def test_explorer_tuning_applies_dispatch_knobs_and_explicit_wins():
    """The Explorer consumes every Tier-A knob it can honor — chunk,
    refill lane width, dispatch_steps, pipeline — with the same
    omitted-arg sentinel rule as run_batch (a cached `devices` stays
    unconsumed: island topology belongs to the Federation)."""
    from madsim_tpu.explore import Explorer
    from madsim_tpu.tpu.engine import DEFAULT_DISPATCH_STEPS

    wl = _raft_workload()
    tn = {"dispatch_steps": 123, "pipeline": False, "chunk": 8,
          "refill_lanes": 4}
    ex = Explorer(wl, lanes=16, tuning=tn)
    assert ex.dispatch_steps == 123
    assert ex.pipeline is False
    assert ex.chunk == 8
    assert ex.refill_lanes == 4
    # explicit arguments win over every tuned value
    ex2 = Explorer(
        wl, lanes=16, chunk=16, refill_lanes=8, dispatch_steps=456,
        pipeline=True, tuning=tn, sim=ex.sim,
    )
    assert ex2.dispatch_steps == 456
    assert ex2.pipeline is True
    assert ex2.chunk == 16
    assert ex2.refill_lanes == 8
    # untuned default: the engine's own segment length
    ex3 = Explorer(wl, lanes=16, sim=ex.sim)
    assert ex3.dispatch_steps == DEFAULT_DISPATCH_STEPS


def test_tier_a_devices_grid_excludes_auto_twin():
    """devices=0 already means a mesh over ALL visible devices, so the
    grid must not also list D — the twin would measure one configuration
    twice and a noise win could cache a phantom devices=D 'winner' that
    equals the default."""
    import jax

    wl = _raft_workload()
    ks = {k.name: k for k in tune.tier_a_knobs(wl, n_seeds=32)}
    D = len(jax.devices())
    if D > 1:
        vals = ks["devices"].values
        assert 0 in vals and D not in vals


def test_tune_workload_buckets_by_measured_scale():
    """The cache key's lane bucket is the MEASURED sweep size, not the
    requested lane count: knobs do not transfer across scale, so a
    `--lanes 4096 --seeds 8` run must write under l8 where only an
    8-seed consumer resolves it — never under l4096."""
    wl = _raft_workload(0.2)
    entry = tune.tune_workload(
        wl, "raft", lanes=4_096, n_seeds=8, knobs=(), save=False,
        guard_rounds=1,
    )
    assert entry.lane_bucket == tune.lane_bucket(8)


def test_tier_b_grids_center_on_engine_effective_depth():
    """Tier-B candidates are centered on the depths the engine actually
    derives for the default config (msg_depth_msg=None => capacity//C),
    and tier_b_effective_defaults names that value — so an
    effective-equal candidate is recognizable as the default program and
    can never be cached as a hash-moving no-op 'win'."""
    from madsim_tpu.tpu.engine import BatchedSim

    wl = _raft_workload()
    sim0 = BatchedSim(wl.spec, wl.config)
    ks = {k.name: k for k in tune.tier_b_config_knobs(wl)}
    assert int(sim0._Km) in ks["msg_depth_msg"].values
    eff = tune.tier_b_effective_defaults(wl, {"msg_depth_msg": None})
    assert eff["msg_depth_msg"] == int(sim0._Km)


# --------------------------------------------------------------- Tier-B gate


@pytest.mark.chaos
def test_tier_b_gate_rejects_planted_dropping_config():
    """The planted drop-inducing pool depth next to its clean twin: the
    gate's overflow leg must fire on the squeezed budget and stay quiet
    on the shipped one (which also re-earns its range certificate)."""
    wl = _raft_workload()
    clean = tune.tier_b_gate(wl, wl.config, seeds=48, certify=True)
    assert clean["ok"], clean["reasons"]
    planted = dataclasses.replace(
        wl.config, msg_capacity=8, msg_depth_msg=None
    )
    bad = tune.tier_b_gate(wl, planted, seeds=48, certify=False)
    assert not bad["ok"]
    assert any("overflow" in r for r in bad["reasons"])


def test_tier_b_gate_rejects_engine_refused_config():
    """Leg 1: a config the BatchedSim constructor refuses (here the
    narrow-horizon derating family of validations) is a gate reject with
    the constructor's own message, not a crash."""
    wl = _raft_workload()
    bad = dataclasses.replace(wl.config, msg_spare_slots=-1)
    gate = tune.tier_b_gate(wl, bad, seeds=8, certify=False)
    assert not gate["ok"]
    assert any("engine rejects" in r for r in gate["reasons"])


def test_apply_tier_b_requires_certification():
    from madsim_tpu.tpu.spec import SimConfig

    cfg = SimConfig()
    entry = tune.TunedEntry(
        device_kind="cpu", workload="raft", config_hash="x",
        lane_bucket=64, config={"msg_spare_slots": 2}, certified=False,
    )
    with pytest.raises(ValueError, match="certified"):
        tune.apply_tier_b(cfg, entry)
    entry.certified = True
    out = tune.apply_tier_b(cfg, entry)
    assert out.msg_spare_slots == 2
    assert out.hash() != cfg.hash()  # Tier B moves the config identity


# ------------------------------------------------------- search machinery


def test_coordinate_descent_picks_fast_value_and_guard_falls_back():
    """Pure-host search check: a deterministic fake clock makes value 7
    fastest; the descent must find it, and the A/B guard must keep the
    default when the 'tuned' assignment measures slower."""
    walls = {1: 0.9, 4: 0.5, 7: 0.2}

    def fake_measure(assign, rep):
        return walls[assign["k"]]

    tl = tune.TrialLog()
    best = tune.coordinate_descent(
        (tune.Knob("k", (1, 4, 7)),), fake_measure, {"k": 1}, tl
    )
    assert best == {"k": 7}
    assert all(t["knob"] in ("k",) for t in tl.trials)

    meds = tune.ab_guard(
        lambda a, rep: 1.0 if a["k"] == 7 else 0.5,  # tuned slower now
        {"k": 1}, {"k": 7}, tl,
    )
    assert meds["tuned"] >= meds["default"]  # caller falls back


def test_guard_tier_a_falls_back_and_accounts():
    """The hoisted never-regress guard: a losing assignment is replaced
    by the default, and the seeds/s accounting reflects the default."""
    tl = tune.TrialLog()
    best, fallback, base_sps, tuned_sps = tune._guard_tier_a(
        lambda a, rep: 1.0 if a["k"] == 7 else 0.5,
        {"k": 1}, {"k": 7}, tl, work_items=10, guard_rounds=1,
    )
    assert fallback and best == {"k": 1}
    assert base_sps == tuned_sps == 10 / 0.5


def test_tier_b_measured_under_post_guard_tier_a(monkeypatch):
    """Ordering regression: the Tier-A never-regress guard runs BEFORE
    the Tier-B pass, so Tier-B candidates are measured (and certified)
    under the dispatch shape the entry actually ships. Guarding after
    would let the guard discard the assignment the Tier-B win was
    measured under — a cached entry that can be a slowdown."""
    wl = _raft_workload(0.2)
    doctored = {}

    def fake_descent(knobs, measure, default, tl):
        doctored.update(default, chunk=2)  # a "winner" the guard rejects
        return dict(doctored)

    def fake_ab_guard(measure, default, best, tl, rounds=2):
        return {"default": 0.5, "tuned": 1.0}  # tuned measures slower

    seen = {}

    def spy_tier_b(workload, tier_a, n_seeds, tl, **kw):
        seen["tier_a"] = dict(tier_a)
        return {}, {}, False

    monkeypatch.setattr(tune, "coordinate_descent", fake_descent)
    monkeypatch.setattr(tune, "ab_guard", fake_ab_guard)
    monkeypatch.setattr(tune, "_tune_tier_b", spy_tier_b)
    entry = tune.tune_workload(
        wl, "raft", lanes=8, n_seeds=8, tier="AB", save=False
    )
    # the Tier-B pass saw the POST-guard (default) assignment, not the
    # discarded descent winner
    assert seen["tier_a"]["chunk"] == 8
    assert seen["tier_a"] != doctored
    assert entry.fallback and entry.dispatch == {}


def test_campaign_tuning_applies_pipeline(tmp_path):
    """Campaign leaves `pipeline` on the Explorer's None sentinel so a
    tuned pipeline knob actually lands (a silently-unapplied knob next
    to a checkpoint that claims it was applied is the r10 dropped-mesh
    class); the checkpoint's explorer_params record the APPLIED value,
    which resume replays explicitly."""
    from madsim_tpu.campaign import Campaign, explorer_params

    wl = _raft_workload(0.2)
    c = Campaign(
        wl, str(tmp_path / "c1"), lanes=8, tuning={"pipeline": False}
    )
    assert c.ex.pipeline is False
    assert explorer_params(c.ex)["pipeline"] is False
    # an explicit argument still wins over the tuned dict
    c2 = Campaign(
        wl, str(tmp_path / "c2"), lanes=8, sim=c.ex.sim,
        tuning={"pipeline": False}, pipeline=True,
    )
    assert c2.ex.pipeline is True


def test_trial_log_routes_through_metrics_registry(tmp_path):
    """Satellite: tuning trials ride the r11 metrics registry — a
    per-knob trial counter, the measured-ms histogram, and a span per
    trial on the wall-clock timeline."""
    from madsim_tpu import telemetry

    telemetry.enable(out_dir=str(tmp_path))
    try:
        tl = tune.TrialLog()
        tl.trial(lambda a, rep: 0.01, {"k": 1}, "refill_lanes", 1)
        tl.trial(lambda a, rep: 0.02, {"k": 2}, "refill_lanes", 2)
        reg = telemetry.get_registry()
        assert reg.counter("tune_trials_total").value(
            knob="refill_lanes"
        ) == 2
        snap = reg.histogram("tune_trial_ms").snapshot(knob="refill_lanes")
        assert snap and snap["count"] == 2
        assert any(s.name == "tune_trial" for s in telemetry.spans())
    finally:
        telemetry.disable()


@pytest.mark.chaos
def test_tune_workload_writes_the_key_consumers_resolve(tmp_path):
    """THE silent-no-op regression: the cache identity is the SPEC name
    ("raft5"), because that is what every tuning="auto" consumer
    (run_batch, Campaign, Explorer, ttfb, shrink_seed) resolves with —
    an entry written under the registry/CLI name ("raft") would never be
    found and auto-tuning would silently run defaults everywhere."""
    wl = _raft_workload(0.2)
    entry = tune.tune_workload(
        wl, "raft", lanes=8, n_seeds=8, knobs=(),
        cache_dir=str(tmp_path), save=True, guard_rounds=1,
    )
    assert entry.workload == wl.spec.name == "raft5"
    cfg = wl.config
    assert tune.load_tuned(
        wl.spec.name, cfg, 8, dir=str(tmp_path)
    ) == entry
    # and the consumer-side resolve path sees it
    assert tune.resolve_tuning(
        "auto", wl.spec.name, cfg, 8, dir=str(tmp_path)
    ) == entry.dispatch


# ------------------------------------------------ campaign resume conflicts


def test_check_resume_conflicts_on_tuning():
    from madsim_tpu.campaign import check_resume_conflicts

    man = {
        "params": {"meta_seed": 0, "lanes": 16, "chunk": 16},
        "workload": {"name": "raft", "virtual_secs": 1.0},
        "tuning": {"chunk": 64, "refill_lanes": 8},
    }
    # same tuning: fine; omitted: defers to the checkpoint
    check_resume_conflicts(man, {"tuning": {"chunk": 64, "refill_lanes": 8}})
    check_resume_conflicts(man, {})
    # a DIFFERENT tuned dict (another tuned cache): loud reject
    with pytest.raises(ValueError, match="tuning"):
        check_resume_conflicts(man, {"tuning": {"chunk": 32}})
    # checkpoint tuned, request pinning defaults: loud reject too
    with pytest.raises(ValueError, match="tuning"):
        check_resume_conflicts(man, {"tuning": None})
    # untuned checkpoint accepts only untuned pins
    man2 = dict(man, tuning=None)
    check_resume_conflicts(man2, {"tuning": None})
    with pytest.raises(ValueError, match="tuning"):
        check_resume_conflicts(man2, {"tuning": {"chunk": 64}})


def test_serve_request_auto_tuning_resolves_before_conflict_check(
    tmp_path, monkeypatch,
):
    """A service request with "tuning": "auto" must RESUME cleanly while
    the tuned cache is unchanged: the raw string resolves against the
    checkpoint's own workload + lane scale BEFORE the conflict check, so
    the comparison is resolved-vs-resolved, never "auto" vs a dict."""
    from madsim_tpu.campaign import (
        _explicit_request_params, check_resume_conflicts,
        named_workload_ref,
    )
    from madsim_tpu.explore import _named_workload

    monkeypatch.setenv("MADSIM_TUNED_DIR", str(tmp_path))
    man = {
        "workload": named_workload_ref("raft", 0.5, False),
        "params": {"meta_seed": 0, "lanes": 16, "chunk": 16},
        "tuning": None,
    }
    # clean cache miss: "auto" resolves to None == the checkpoint's None
    given = _explicit_request_params({"tuning": "auto"}, man)
    assert given["tuning"] is None
    check_resume_conflicts(man, given)
    # cache populated with the SAME dict the checkpoint persisted:
    # restart with "auto" still resumes
    wl = _named_workload("raft", 0.5, False)
    tune.TunedEntry(
        device_kind=tune.device_kind(), workload=wl.spec.name,
        config_hash=tune.config_hash_sans_tier_b(wl.config),
        lane_bucket=tune.lane_bucket(16),
        dispatch={"chunk": 8},
    ).save(str(tmp_path))
    man2 = dict(man, tuning={"chunk": 8})
    given2 = _explicit_request_params({"tuning": "auto"}, man2)
    assert given2["tuning"] == {"chunk": 8}
    check_resume_conflicts(man2, given2)
    # a re-tuned cache (different dict) against the old checkpoint: loud
    with pytest.raises(ValueError, match="tuning"):
        check_resume_conflicts(man, given2)


@pytest.mark.chaos
def test_campaign_persists_tuning_and_rejects_resume_drift(tmp_path):
    """The checkpoint persists the RESOLVED tuning; resume replays it
    (never re-tunes) and a resume under a different tuned dict is a loud
    reject — the r10 'silently dropped mesh' bug class."""
    from madsim_tpu.campaign import Campaign

    from tests.test_explore import _planted_workload

    wl = _planted_workload()
    c = Campaign(
        wl, str(tmp_path / "c1"), meta_seed=3, lanes=8,
        shrink=False, tuning={"chunk": 4, "refill_lanes": 4},
    )
    assert c.tuning == {"chunk": 4, "refill_lanes": 4}
    assert c.ex.chunk == 4 and c.ex.refill_lanes == 4
    c.checkpoint()
    with open(tmp_path / "c1" / "manifest.json") as f:
        man = json.load(f)
    assert man["tuning"] == {"chunk": 4, "refill_lanes": 4}
    # resume without tuning= replays the persisted tuning verbatim
    c2 = Campaign.resume(str(tmp_path / "c1"), workload=wl)
    assert c2.tuning == {"chunk": 4, "refill_lanes": 4}
    assert c2.ex.chunk == 4 and c2.ex.refill_lanes == 4
    # resume under a different tuned cache: loud reject
    with pytest.raises(ValueError, match="tuning"):
        Campaign.resume(
            str(tmp_path / "c1"), workload=wl, tuning={"chunk": 8}
        )
    # resume under the SAME tuning: fine
    c3 = Campaign.resume(
        str(tmp_path / "c1"), workload=wl,
        tuning={"chunk": 4, "refill_lanes": 4},
    )
    assert c3.tuning == c.tuning


# ------------------------------------------------------------ shrink wiring


def test_shrink_seed_accepts_tuning_lane_width():
    """triage.shrink_seed(tuning=...) adopts the tuned refill lane width
    only where the caller kept the default (signature-level check: the
    resolve path runs and an explicit width still wins)."""
    import inspect

    from madsim_tpu import triage

    sig = inspect.signature(triage.shrink_seed)
    assert "tuning" in sig.parameters
