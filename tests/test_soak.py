"""Long-horizon virtual-time soak: the unbounded-clock contract.

r3's engine clock was int32 microseconds with INF at 2^31-1, capping a lane
at ~35.8 virtual MINUTES — long-horizon fuzzing (lease-expiry cascades,
multi-hour clock-skew bugs) could not even be expressed. The r4 engine
keeps hot-path arithmetic int32 but rebases each lane's epoch every
REBASE_US (~268 s), so virtual time is effectively unbounded
(~2^59 us; see spec.REBASE_US for why not int64 tensors). These tests run
a slow-timer Raft config PAST the old cap and assert the simulation
arithmetic stays exact across dozens of rebases."""

import numpy as np
import jax.numpy as jnp
import pytest

from madsim_tpu.tpu import (
    BatchedSim,
    REBASE_US,
    SimConfig,
    abs_time_us,
    make_raft_spec,
    summarize,
)
from madsim_tpu.tpu.kv import kv_workload


def slow_raft(heartbeat_s=5.0):
    """Raft with multi-second timers: virtual hours in a few thousand
    steps (the step count scales with EVENTS, not with virtual time)."""
    return make_raft_spec(
        n_nodes=5,
        heartbeat_us=int(heartbeat_s * 1e6),
        election_lo_us=int(heartbeat_s * 3e6),
        election_hi_us=int(heartbeat_s * 6e6),
        client_rate=0.2,
    )


def test_virtual_time_past_the_old_int32_cap():
    # 45 virtual minutes > the r3 hard cap of ~35.8 min (2^31 us)
    sim = BatchedSim(
        slow_raft(),
        SimConfig(horizon_us=45 * 60 * 1_000_000, loss_rate=0.05),
    )
    state = sim.run(jnp.arange(16), max_steps=40_000)
    s = summarize(state, sim.spec)
    assert s["violations"] == 0 and s["deadlocked"] == 0
    t = abs_time_us(state)
    assert (t >= 45 * 60 * 1_000_000).all()  # every lane crossed the cap
    assert int(np.asarray(state.epoch).min()) >= 10  # many rebases ran
    # offsets stayed small (the whole point): int32 with huge headroom
    assert int(np.asarray(state.clock).max()) < REBASE_US + (1 << 27)


@pytest.mark.deep
def test_two_hour_soak_no_saturation():
    """The VERDICT r3 #6 done-condition: a 2-hour-virtual-time soak runs
    without saturation — ~27 epochs of rebasing, timers/elections/chaos
    arithmetic all exact to the end."""
    sim = BatchedSim(
        slow_raft(),
        SimConfig(
            horizon_us=2 * 3600 * 1_000_000,
            loss_rate=0.05,
            crash_interval_lo_us=60_000_000,
            crash_interval_hi_us=300_000_000,
            restart_delay_lo_us=10_000_000,
            restart_delay_hi_us=60_000_000,
        ),
    )
    state = sim.run(jnp.arange(32), max_steps=200_000)
    s = summarize(state, sim.spec)
    assert s["violations"] == 0 and s["deadlocked"] == 0
    t = abs_time_us(state)
    assert (t >= 2 * 3600 * 1_000_000).all()
    assert int(np.asarray(state.epoch).min()) >= 26  # 2 h / 268 s epochs
    # the protocol made continuous progress the whole way: commits kept
    # advancing (a saturated/frozen lane would stall them)
    assert int(np.asarray(state.node.commit).min()) > 100


@pytest.mark.deep
def test_kv_time_fields_rebase_across_epochs():
    """kv stores absolute times in its state + histories (time_fields);
    a multi-epoch run must keep `now - field` arithmetic and the history
    real-time order valid — violations would fire otherwise, and the
    watermark times must stay in the current basis (< REBASE + slack)."""
    wl = kv_workload(virtual_secs=900.0)  # ~3.3 epochs
    # 900 virtual seconds exceeds kv's CERTIFIED narrow-epoch horizon
    # (~218 s: the range certifier re-classified the u16 epoch bound as
    # a rate argument — see tpu/kv.py rate_floors — and the engine now
    # refuses longer narrow soaks). This test is about time_fields
    # rebasing, not the narrow table: run it wide, the documented
    # long-soak path. Narrowing invariance is pinned separately in
    # test_state_layout.py.
    import dataclasses

    spec = dataclasses.replace(wl.spec, narrow_fields=None)
    sim = BatchedSim(spec, wl.config)
    state = sim.run(jnp.arange(4), max_steps=1_200_000, dispatch_steps=50_000)
    s = summarize(state, wl.spec)
    assert s["violations"] == 0
    assert int(np.asarray(state.epoch).min()) >= 3
    assert int(np.asarray(state.node.wm_t).max()) < REBASE_US + (1 << 27)
    assert s["mean_acked_ops"] > 1000
