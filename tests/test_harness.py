"""Test-harness tests: seed sweep, env config, determinism check, buggify."""

import os

import pytest

import madsim_tpu as ms
from madsim_tpu.testing import Builder, TestFailure, madsim_test


def test_builder_sweeps_seeds():
    seen = []

    async def body():
        seen.append(ms.Handle.current().seed)

    Builder(seed=100, count=5).run(lambda: body())
    assert seen == [100, 101, 102, 103, 104]


def test_builder_jobs_forked_processes(tmp_path):
    # jobs>1 forks worker PROCESSES (true per-seed CPU parallelism, matching
    # the reference's thread-per-seed model in Rust where threads really run
    # in parallel); results come back over pipes, so the bodies talk to the
    # parent via the filesystem here
    async def body():
        seed = ms.Handle.current().seed
        (tmp_path / f"seed{seed}").write_text(str(os.getpid()))
        return seed

    out = Builder(seed=10, count=8, jobs=4).run(lambda: body())
    assert out == 17  # the last seed's result
    ran = sorted(int(p.name[4:]) for p in tmp_path.glob("seed*"))
    assert ran == list(range(10, 18))
    pids = {(tmp_path / f"seed{s}").read_text() for s in ran}
    assert len(pids) == 4  # really 4 distinct worker processes
    assert str(os.getpid()) not in pids


def test_builder_jobs_failure_reports_seed_across_fork():
    async def body():
        if ms.Handle.current().seed == 13:
            raise RuntimeError("found a bug")

    with pytest.raises(TestFailure, match="MADSIM_TEST_SEED=13"):
        Builder(seed=10, count=8, jobs=4).run(lambda: body())


def test_builder_jobs_worker_death_blames_in_flight_seed():
    # per-seed result frames mean a worker that dies mid-seed is blamed on
    # the seed it was actually running, not the first seed of its share
    async def body():
        if ms.Handle.current().seed == 16:  # 3rd seed of worker 0's share
            os._exit(42)  # simulated hard crash: no exception, no frame

    with pytest.raises(TestFailure, match="MADSIM_TEST_SEED=16"):
        Builder(seed=10, count=8, jobs=2).run(lambda: body())


def test_builder_jobs_unpicklable_result_degrades_only_itself():
    from madsim_tpu.testing import UnpicklableResult

    async def body():
        if ms.Handle.current().seed == 17:  # the returned (last) seed
            return lambda: None  # unpicklable
        return ms.Handle.current().seed

    out = Builder(seed=10, count=8, jobs=4).run(lambda: body())
    assert isinstance(out, UnpicklableResult)
    assert "lambda" in out.repr or "function" in out.repr


def _machine_parallelism() -> float:
    """Raw fork calibration: ratio of 2-parallel-burns wall to 1 burn.

    Sandboxed CI often advertises N vCPUs but delivers ~1 core of real
    throughput; the framework can't beat physics, so the speedup assertion
    only runs where parallel forks actually overlap (ratio well under 2).
    """
    import time as _time

    def burn() -> int:
        x = 1
        for _ in range(2_000_000):
            x = (x * 1103515245 + 12345) & 0xFFFFFFFF
        return x

    t0 = _time.perf_counter()
    burn()
    one = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    pids = []
    for _ in range(2):
        pid = os.fork()
        if pid == 0:  # child burns and exits immediately — no grandchildren
            burn()
            os._exit(0)
        pids.append(pid)
    for pid in pids:
        os.waitpid(pid, 0)
    two = _time.perf_counter() - t0
    return two / one


def test_builder_jobs_parallel_speedup():
    # the round-2 weakness: GIL-bound thread jobs gave no speedup. Forked
    # jobs give real per-seed CPU parallelism wherever the machine has it.
    # Calibrate first: throttled/shared sandboxes advertise N vCPUs but
    # deliver ~1 core erratically — assert timing only where two raw
    # forked burns reliably overlap (best of 2 trials, solidly parallel);
    # elsewhere still assert the fork MECHANISM end to end (workers fork,
    # every seed runs, results return) so the test never silently skips.
    import time as _time

    async def body():
        x = ms.Handle.current().seed
        for _ in range(600_000):
            x = (x * 1103515245 + 12345) & 0xFFFFFFFF
        return x

    can_parallel = min(_machine_parallelism(), _machine_parallelism()) <= 1.4

    t0 = _time.perf_counter()
    r_serial = Builder(seed=0, count=8, jobs=1).run(lambda: body())
    serial = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    r_forked = Builder(seed=0, count=8, jobs=2).run(lambda: body())
    forked = _time.perf_counter() - t0
    # same seeds => same last-seed result, whichever worker ran it
    assert r_forked == r_serial
    if can_parallel:
        assert forked < serial / 1.3, (serial, forked)


def test_failure_reports_repro_seed():
    async def body():
        if ms.Handle.current().seed == 7:
            raise RuntimeError("found a bug")

    with pytest.raises(TestFailure, match="MADSIM_TEST_SEED=7"):
        Builder(seed=5, count=5).run(lambda: body())


def test_env_config(monkeypatch, tmp_path):
    cfg = tmp_path / "cfg.toml"
    cfg.write_text('[net]\npacket_loss_rate = 0.5\nsend_latency = "2ms..4ms"\n')
    monkeypatch.setenv("MADSIM_TEST_SEED", "33")
    monkeypatch.setenv("MADSIM_TEST_NUM", "2")
    monkeypatch.setenv("MADSIM_TEST_CONFIG", str(cfg))
    monkeypatch.setenv("MADSIM_TEST_TIME_LIMIT", "60")

    b = Builder.from_env()
    assert (b.seed, b.count, b.time_limit) == (33, 2, 60.0)
    assert b.config.net.packet_loss_rate == 0.5
    assert b.config.net.send_latency_min == 0.002

    seeds = []

    async def body():
        h = ms.Handle.current()
        assert h.config.net.packet_loss_rate == 0.5
        seeds.append(h.seed)

    b.run(lambda: body())
    assert seeds == [33, 34]


def test_check_determinism_mode():
    async def body():
        for _ in range(5):
            await ms.time.sleep(ms.rand())

    Builder(seed=3, count=2, check=True).run(lambda: body())


def test_madsim_test_decorator(monkeypatch):
    monkeypatch.setenv("MADSIM_TEST_SEED", "42")
    calls = []

    @madsim_test
    async def my_test():
        calls.append(ms.Handle.current().seed)

    my_test()
    assert calls == [42]


def test_time_limit_from_builder():
    async def body():
        await ms.time.sleep(1e6)

    with pytest.raises(TestFailure):
        Builder(seed=1, time_limit=10.0).run(lambda: body())


def test_buggify_fires_when_enabled():
    rt = ms.Runtime(seed=9)

    async def main():
        assert not ms.buggify.is_enabled()
        assert not ms.buggify.buggify()  # disabled => never fires
        ms.buggify.enable()
        fired = sum(1 for _ in range(1000) if ms.buggify.buggify())
        always = sum(1 for _ in range(100) if ms.buggify.buggify_with_prob(1.0))
        ms.buggify.disable()
        return fired, always

    fired, always = rt.block_on(main())
    assert 150 < fired < 350  # ~25%
    assert always == 100
