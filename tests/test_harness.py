"""Test-harness tests: seed sweep, env config, determinism check, buggify."""

import os

import pytest

import madsim_tpu as ms
from madsim_tpu.testing import Builder, TestFailure, madsim_test


def test_builder_sweeps_seeds():
    seen = []

    async def body():
        seen.append(ms.Handle.current().seed)

    Builder(seed=100, count=5).run(lambda: body())
    assert seen == [100, 101, 102, 103, 104]


def test_builder_jobs_threads():
    seen = []

    async def body():
        seen.append(ms.Handle.current().seed)

    Builder(seed=10, count=8, jobs=4).run(lambda: body())
    assert sorted(seen) == list(range(10, 18))


def test_failure_reports_repro_seed():
    async def body():
        if ms.Handle.current().seed == 7:
            raise RuntimeError("found a bug")

    with pytest.raises(TestFailure, match="MADSIM_TEST_SEED=7"):
        Builder(seed=5, count=5).run(lambda: body())


def test_env_config(monkeypatch, tmp_path):
    cfg = tmp_path / "cfg.toml"
    cfg.write_text('[net]\npacket_loss_rate = 0.5\nsend_latency = "2ms..4ms"\n')
    monkeypatch.setenv("MADSIM_TEST_SEED", "33")
    monkeypatch.setenv("MADSIM_TEST_NUM", "2")
    monkeypatch.setenv("MADSIM_TEST_CONFIG", str(cfg))
    monkeypatch.setenv("MADSIM_TEST_TIME_LIMIT", "60")

    b = Builder.from_env()
    assert (b.seed, b.count, b.time_limit) == (33, 2, 60.0)
    assert b.config.net.packet_loss_rate == 0.5
    assert b.config.net.send_latency_min == 0.002

    seeds = []

    async def body():
        h = ms.Handle.current()
        assert h.config.net.packet_loss_rate == 0.5
        seeds.append(h.seed)

    b.run(lambda: body())
    assert seeds == [33, 34]


def test_check_determinism_mode():
    async def body():
        for _ in range(5):
            await ms.time.sleep(ms.rand())

    Builder(seed=3, count=2, check=True).run(lambda: body())


def test_madsim_test_decorator(monkeypatch):
    monkeypatch.setenv("MADSIM_TEST_SEED", "42")
    calls = []

    @madsim_test
    async def my_test():
        calls.append(ms.Handle.current().seed)

    my_test()
    assert calls == [42]


def test_time_limit_from_builder():
    async def body():
        await ms.time.sleep(1e6)

    with pytest.raises(TestFailure):
        Builder(seed=1, time_limit=10.0).run(lambda: body())


def test_buggify_fires_when_enabled():
    rt = ms.Runtime(seed=9)

    async def main():
        assert not ms.buggify.is_enabled()
        assert not ms.buggify.buggify()  # disabled => never fires
        ms.buggify.enable()
        fired = sum(1 for _ in range(1000) if ms.buggify.buggify())
        always = sum(1 for _ in range(100) if ms.buggify.buggify_with_prob(1.0))
        ms.buggify.disable()
        return fired, always

    fired, always = rt.block_on(main())
    assert 150 < fired < 350  # ~25%
    assert always == 100
