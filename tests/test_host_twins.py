"""Every device protocol has a debuggable host twin, and each canonical
planted bug reproduces on BOTH faces (VERDICT r4 missing #1).

The repo's contract (tpu/batch.py BatchWorkload): a workload provides the
device wide net AND a host-runtime reproducer, mirroring the reference's
everything-is-a-debuggable-multi-node-sim pattern
(/root/reference/tonic-example/tests/test.rs:155-278). raft and kv have
had twins since r3/r4; these cover the r5 additions (2PC, Paxos).
"""

import pytest

from madsim_tpu.workloads import paxos_host, twopc_host


def test_twopc_host_twin_clean():
    r = twopc_host.fuzz_one_seed(3, virtual_secs=6.0)
    assert r["decided_records"] > 0
    assert r["txns_started"] > 10


def test_twopc_planted_bug_reproduces_on_host_face():
    """The canonical wrong participant (in-doubt timeout unilaterally
    aborts) violates atomicity on the host twin at a pinned seed."""
    with pytest.raises(twopc_host.InvariantViolation, match="atomicity"):
        twopc_host.fuzz_one_seed(0, virtual_secs=10.0, buggy=True)


def test_twopc_planted_bug_reproduces_on_device_face():
    """The same bug class on the device face (the impatient-timer spec of
    test_tpu_twopc exercises the full fuzz; this is the compact BOTH-faces
    witness next to the host one)."""
    import dataclasses

    import jax.numpy as jnp

    from madsim_tpu.tpu import BatchedSim, summarize
    from madsim_tpu.tpu.twopc import twopc_workload

    wl = twopc_workload(virtual_secs=8.0)
    from tests.test_buggify import unilateral_abort_spec

    buggy = unilateral_abort_spec()
    sim = BatchedSim(buggy, wl.config)
    state = sim.run(jnp.arange(192), max_steps=40_000)
    assert summarize(state)["violations"] > 0
    del dataclasses


def test_paxos_host_twin_clean():
    r = paxos_host.fuzz_one_seed(1, virtual_secs=8.0)
    assert r["decided_nodes"] >= 3  # a majority learned the decision
    assert r["value"] != 0


def test_paxos_planted_bug_reproduces_on_both_faces():
    """The canonical Paxos mistake (phase 2 ignores the discovered
    accepted value) splits agreement on BOTH faces."""
    # host face, pinned seed (found by sweeping seeds 0..23: 0, 17, 18 hit)
    with pytest.raises(paxos_host.InvariantViolation, match="agreement"):
        paxos_host.fuzz_one_seed(0, virtual_secs=10.0, buggy=True)

    # device face: the same bug over a seed batch
    import jax.numpy as jnp

    from madsim_tpu.tpu import BatchedSim, summarize
    from madsim_tpu.tpu.paxos import make_paxos_spec, paxos_workload

    wl = paxos_workload(virtual_secs=8.0)
    sim = BatchedSim(
        make_paxos_spec(5, buggy_ignore_discovered=True), wl.config
    )
    state = sim.run(jnp.arange(256), max_steps=40_000)
    assert summarize(state)["violations"] > 0


@pytest.mark.chaos
def test_raft_fault_plan_chaos_stream_agrees_host_vs_tpu():
    """The nemesis tentpole's twin contract: ONE FaultPlan + ONE seed gives
    the SAME schedule-level chaos event stream on both backends.

    Chain of equality, all ends anchored to `plan.schedule(seed, ...)`
    (the pure murmur3 derivation both backends mirror):
      host:   NemesisDriver.applied      == schedule
      device: traced engine chaos events == schedule
      plus the per-node clock-skew assignments agree bit-for-bit.
    """
    import dataclasses

    import madsim_tpu as ms
    from madsim_tpu import nemesis
    from madsim_tpu.workloads.raft_host import RaftNode

    N, SEED, HOR_US = 5, 5, 3_000_000
    plan = nemesis.FaultPlan(
        name="raft-twin",
        clauses=(
            nemesis.Crash(interval_lo_us=400_000, interval_hi_us=1_200_000,
                          down_lo_us=300_000, down_hi_us=900_000),
            nemesis.Partition(interval_lo_us=500_000, interval_hi_us=1_500_000,
                              heal_lo_us=400_000, heal_hi_us=1_200_000),
            nemesis.ClockSkew(max_ppm=20_000),
        ),
    )
    sched = plan.schedule(SEED, HOR_US, N)
    assert len([e for e in sched if e.kind != "skew"]) >= 4

    # -- host face: real RaftNodes under the driver ---------------------
    async def host_body():
        handle = ms.Handle.current()
        addrs = [f"10.0.1.{i + 1}:6000" for i in range(N)]
        rafts = [RaftNode(i, N, addrs) for i in range(N)]
        nodes = []
        for i in range(N):
            node = (
                handle.create_node().name(f"raft-{i}").ip(f"10.0.1.{i + 1}")
                .init(lambda i=i: rafts[i].run()).build()
            )
            nodes.append(node)
        driver = nemesis.NemesisDriver(
            plan, handle, [nd.id for nd in nodes], horizon_us=HOR_US,
        )
        driver.install()
        t = ms.time.current()
        end = t.elapsed() + HOR_US / 1e6
        while t.elapsed() < end:
            await ms.time.sleep(0.02)
        return driver

    rt = ms.Runtime(seed=SEED)
    driver = rt.block_on(host_body())
    assert driver.applied == [e for e in sched if e.kind != "skew"]
    host_fires = rt.handle.metrics().chaos_fires()
    assert host_fires["crash"] > 0 and host_fires["partition"] > 0
    assert host_fires["skew"] == sum(
        1 for p in plan.skew_ppm(SEED, N) if p != 0
    )

    # -- device face: same plan compiled onto the batched engine --------
    import numpy as np

    from madsim_tpu.tpu import BatchedSim, SimConfig, make_raft_spec
    from madsim_tpu.tpu import nemesis as tpu_nemesis

    cfg = tpu_nemesis.compile_plan(plan, SimConfig(horizon_us=HOR_US))
    sim = BatchedSim(make_raft_spec(N), cfg)
    n_events = tpu_nemesis.assert_device_matches_schedule(
        sim, plan, SEED, horizon_us=HOR_US
    )
    assert n_events >= 4
    # skew assignments: engine init state vs the pure mirror
    import jax.numpy as jnp

    st = sim.init(jnp.asarray([SEED], jnp.uint32))
    dev_ppm = np.round(
        (np.asarray(st.nem.skew)[0] - 1.0) * 1e6
    ).astype(int).tolist()
    assert dev_ppm == plan.skew_ppm(SEED, N)
    del dataclasses


def test_workloads_wire_host_repro():
    """All four protocols are debuggable from a violating seed: the
    workload factories ship a host_repro (VERDICT r4: twopc and paxos
    shipped host_repro=None)."""
    from madsim_tpu.tpu import raft_workload
    from madsim_tpu.tpu.kv import kv_workload
    from madsim_tpu.tpu.paxos import paxos_workload
    from madsim_tpu.tpu.twopc import twopc_workload

    for wl in (
        raft_workload(), kv_workload(), twopc_workload(), paxos_workload()
    ):
        assert wl.host_repro is not None

    # and the repro runs end to end for the r5 twins (clean seed)
    out = twopc_workload(virtual_secs=4.0).host_repro(5)
    assert out["violations"] == 0
    out = paxos_workload(virtual_secs=4.0).host_repro(5)
    assert out["violations"] == 0
