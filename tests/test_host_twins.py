"""Every device protocol has a debuggable host twin, and each canonical
planted bug reproduces on BOTH faces (VERDICT r4 missing #1).

The repo's contract (tpu/batch.py BatchWorkload): a workload provides the
device wide net AND a host-runtime reproducer, mirroring the reference's
everything-is-a-debuggable-multi-node-sim pattern
(/root/reference/tonic-example/tests/test.rs:155-278). raft and kv have
had twins since r3/r4; these cover the r5 additions (2PC, Paxos).
"""

import pytest

from madsim_tpu.workloads import paxos_host, twopc_host


def test_twopc_host_twin_clean():
    r = twopc_host.fuzz_one_seed(3, virtual_secs=6.0)
    assert r["decided_records"] > 0
    assert r["txns_started"] > 10


def test_twopc_planted_bug_reproduces_on_host_face():
    """The canonical wrong participant (in-doubt timeout unilaterally
    aborts) violates atomicity on the host twin at a pinned seed."""
    with pytest.raises(twopc_host.InvariantViolation, match="atomicity"):
        twopc_host.fuzz_one_seed(0, virtual_secs=10.0, buggy=True)


def test_twopc_planted_bug_reproduces_on_device_face():
    """The same bug class on the device face (the impatient-timer spec of
    test_tpu_twopc exercises the full fuzz; this is the compact BOTH-faces
    witness next to the host one)."""
    import dataclasses

    import jax.numpy as jnp

    from madsim_tpu.tpu import BatchedSim, summarize
    from madsim_tpu.tpu.twopc import twopc_workload

    wl = twopc_workload(virtual_secs=8.0)
    from tests.test_buggify import unilateral_abort_spec

    buggy = unilateral_abort_spec()
    sim = BatchedSim(buggy, wl.config)
    state = sim.run(jnp.arange(192), max_steps=40_000)
    assert summarize(state)["violations"] > 0
    del dataclasses


def test_paxos_host_twin_clean():
    r = paxos_host.fuzz_one_seed(1, virtual_secs=8.0)
    assert r["decided_nodes"] >= 3  # a majority learned the decision
    assert r["value"] != 0


def test_paxos_planted_bug_reproduces_on_both_faces():
    """The canonical Paxos mistake (phase 2 ignores the discovered
    accepted value) splits agreement on BOTH faces."""
    # host face, pinned seed (found by sweeping seeds 0..23: 0, 17, 18 hit)
    with pytest.raises(paxos_host.InvariantViolation, match="agreement"):
        paxos_host.fuzz_one_seed(0, virtual_secs=10.0, buggy=True)

    # device face: the same bug over a seed batch
    import jax.numpy as jnp

    from madsim_tpu.tpu import BatchedSim, summarize
    from madsim_tpu.tpu.paxos import make_paxos_spec, paxos_workload

    wl = paxos_workload(virtual_secs=8.0)
    sim = BatchedSim(
        make_paxos_spec(5, buggy_ignore_discovered=True), wl.config
    )
    state = sim.run(jnp.arange(256), max_steps=40_000)
    assert summarize(state)["violations"] > 0


def test_workloads_wire_host_repro():
    """All four protocols are debuggable from a violating seed: the
    workload factories ship a host_repro (VERDICT r4: twopc and paxos
    shipped host_repro=None)."""
    from madsim_tpu.tpu import raft_workload
    from madsim_tpu.tpu.kv import kv_workload
    from madsim_tpu.tpu.paxos import paxos_workload
    from madsim_tpu.tpu.twopc import twopc_workload

    for wl in (
        raft_workload(), kv_workload(), twopc_workload(), paxos_workload()
    ):
        assert wl.host_repro is not None

    # and the repro runs end to end for the r5 twins (clean seed)
    out = twopc_workload(virtual_secs=4.0).host_repro(5)
    assert out["violations"] == 0
    out = paxos_workload(virtual_secs=4.0).host_repro(5)
    assert out["violations"] == 0
