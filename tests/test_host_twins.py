"""Every device protocol has a debuggable host twin, and each canonical
planted bug reproduces on BOTH faces (VERDICT r4 missing #1).

The repo's contract (tpu/batch.py BatchWorkload): a workload provides the
device wide net AND a host-runtime reproducer, mirroring the reference's
everything-is-a-debuggable-multi-node-sim pattern
(/root/reference/tonic-example/tests/test.rs:155-278). raft and kv have
had twins since r3/r4; these cover the r5 additions (2PC, Paxos).
"""

import pytest

from madsim_tpu.workloads import paxos_host, twopc_host


def test_twopc_host_twin_clean():
    r = twopc_host.fuzz_one_seed(3, virtual_secs=6.0)
    assert r["decided_records"] > 0
    assert r["txns_started"] > 10


def test_twopc_planted_bug_reproduces_on_host_face():
    """The canonical wrong participant (in-doubt timeout unilaterally
    aborts) violates atomicity on the host twin at a pinned seed."""
    with pytest.raises(twopc_host.InvariantViolation, match="atomicity"):
        twopc_host.fuzz_one_seed(0, virtual_secs=10.0, buggy=True)


def test_twopc_planted_bug_reproduces_on_device_face():
    """The same bug class on the device face (the impatient-timer spec of
    test_tpu_twopc exercises the full fuzz; this is the compact BOTH-faces
    witness next to the host one)."""
    import dataclasses

    import jax.numpy as jnp

    from madsim_tpu.tpu import BatchedSim, summarize
    from madsim_tpu.tpu.twopc import twopc_workload

    wl = twopc_workload(virtual_secs=8.0)
    from tests.test_buggify import unilateral_abort_spec

    buggy = unilateral_abort_spec()
    sim = BatchedSim(buggy, wl.config)
    state = sim.run(jnp.arange(192), max_steps=40_000)
    assert summarize(state)["violations"] > 0
    del dataclasses


def test_paxos_host_twin_clean():
    r = paxos_host.fuzz_one_seed(1, virtual_secs=8.0)
    assert r["decided_nodes"] >= 3  # a majority learned the decision
    assert r["value"] != 0


def test_paxos_planted_bug_reproduces_on_both_faces():
    """The canonical Paxos mistake (phase 2 ignores the discovered
    accepted value) splits agreement on BOTH faces."""
    # host face, pinned seed (found by sweeping seeds 0..23: 0, 17, 18 hit)
    with pytest.raises(paxos_host.InvariantViolation, match="agreement"):
        paxos_host.fuzz_one_seed(0, virtual_secs=10.0, buggy=True)

    # device face: the same bug over a seed batch
    import jax.numpy as jnp

    from madsim_tpu.tpu import BatchedSim, summarize
    from madsim_tpu.tpu.paxos import make_paxos_spec, paxos_workload

    wl = paxos_workload(virtual_secs=8.0)
    sim = BatchedSim(
        make_paxos_spec(5, buggy_ignore_discovered=True), wl.config
    )
    state = sim.run(jnp.arange(256), max_steps=40_000)
    assert summarize(state)["violations"] > 0


@pytest.mark.chaos
def test_raft_fault_plan_chaos_stream_agrees_host_vs_tpu():
    """The nemesis tentpole's twin contract: ONE FaultPlan + ONE seed gives
    the SAME schedule-level chaos event stream on both backends.

    Chain of equality, all ends anchored to `plan.schedule(seed, ...)`
    (the pure murmur3 derivation both backends mirror):
      host:   NemesisDriver.applied      == schedule
      device: traced engine chaos events == schedule
      plus the per-node clock-skew assignments agree bit-for-bit.
    """
    import dataclasses

    import madsim_tpu as ms
    from madsim_tpu import nemesis
    from madsim_tpu.workloads.raft_host import RaftNode

    N, SEED, HOR_US = 5, 5, 3_000_000
    plan = nemesis.FaultPlan(
        name="raft-twin",
        clauses=(
            nemesis.Crash(interval_lo_us=400_000, interval_hi_us=1_200_000,
                          down_lo_us=300_000, down_hi_us=900_000),
            nemesis.Partition(interval_lo_us=500_000, interval_hi_us=1_500_000,
                              heal_lo_us=400_000, heal_hi_us=1_200_000),
            nemesis.ClockSkew(max_ppm=20_000),
        ),
    )
    sched = plan.schedule(SEED, HOR_US, N)
    assert len([e for e in sched if e.kind != "skew"]) >= 4

    # -- host face: real RaftNodes under the driver ---------------------
    async def host_body():
        handle = ms.Handle.current()
        addrs = [f"10.0.1.{i + 1}:6000" for i in range(N)]
        rafts = [RaftNode(i, N, addrs) for i in range(N)]
        nodes = []
        for i in range(N):
            node = (
                handle.create_node().name(f"raft-{i}").ip(f"10.0.1.{i + 1}")
                .init(lambda i=i: rafts[i].run()).build()
            )
            nodes.append(node)
        driver = nemesis.NemesisDriver(
            plan, handle, [nd.id for nd in nodes], horizon_us=HOR_US,
        )
        driver.install()
        t = ms.time.current()
        end = t.elapsed() + HOR_US / 1e6
        while t.elapsed() < end:
            await ms.time.sleep(0.02)
        return driver

    rt = ms.Runtime(seed=SEED)
    driver = rt.block_on(host_body())
    assert driver.applied == [e for e in sched if e.kind != "skew"]
    host_fires = rt.handle.metrics().chaos_fires()
    assert host_fires["crash"] > 0 and host_fires["partition"] > 0
    assert host_fires["skew"] == sum(
        1 for p in plan.skew_ppm(SEED, N) if p != 0
    )

    # -- device face: same plan compiled onto the batched engine --------
    import numpy as np

    from madsim_tpu.tpu import BatchedSim, SimConfig, make_raft_spec
    from madsim_tpu.tpu import nemesis as tpu_nemesis

    cfg = tpu_nemesis.compile_plan(plan, SimConfig(horizon_us=HOR_US))
    sim = BatchedSim(make_raft_spec(N), cfg)
    n_events = tpu_nemesis.assert_device_matches_schedule(
        sim, plan, SEED, horizon_us=HOR_US
    )
    assert n_events >= 4
    # skew assignments: engine init state vs the pure mirror
    import jax.numpy as jnp

    st = sim.init(jnp.asarray([SEED], jnp.uint32))
    # r8: the device stores integer ppm directly (no f32 rate round-trip)
    dev_ppm = np.asarray(st.nem.skew_ppm)[0].astype(int).tolist()
    assert dev_ppm == plan.skew_ppm(SEED, N)
    del dataclasses


def test_kv_coverage_bitmap_matches_trace_mirror():
    """The coverage twin invariant (explorer tentpole): the device's
    per-lane coverage bitmap is a pure function of trace-visible event
    fields, so the pure-Python mirror in explore.py recomputes a chaos-free
    kv lane's EXACT bitmap from its TraceRecord stream — the coverage
    analog of the nemesis schedule-mirror contract (the host-side
    derivation and the in-jit accumulation agree bit-for-bit)."""
    import dataclasses

    import numpy as np

    from madsim_tpu.explore import bitmap_from_trace
    from madsim_tpu.tpu import BatchedSim
    from madsim_tpu.tpu.kv import kv_workload

    wl = kv_workload(virtual_secs=1.0, loss_rate=0.0, partitions=False)
    sim = BatchedSim(wl.spec, wl.config, coverage=True)
    for seed in (0, 7):
        state, records = sim.run_traced(seed, max_steps=3_000)
        dev = np.asarray(state.cov.bitmap, np.uint32)[0]
        mirror = bitmap_from_trace(records)
        assert dev.any(), "coverage bitmap must not be empty"
        assert np.array_equal(dev, mirror), (
            f"seed {seed}: device bitmap diverges from the trace mirror "
            f"({int((dev != mirror).sum())} of {dev.size} words differ)"
        )
    del dataclasses


@pytest.mark.chaos
def test_chaos_occurrence_masks_agree_host_schedule_device():
    """The occurrence dimension of the chaos report: which window k of
    each schedule clause APPLIED, indexed by `NemesisEvent.k` on all three
    faces — the pure schedule, the host driver (`occ_fired` /
    RuntimeMetrics.chaos_occ_fired), and the engine's per-lane `occ_fired`
    tensor (summarize's `occfires_<clause>_k<k>` keys)."""
    import madsim_tpu as ms
    import numpy as np
    from madsim_tpu import nemesis
    from madsim_tpu.nemesis import OCC_CLAUSES, OCC_ROW
    from madsim_tpu.workloads.raft_host import RaftNode

    N, SEED, HOR_US = 5, 5, 3_000_000
    plan = nemesis.FaultPlan(
        name="occ-twin",
        clauses=(
            nemesis.Crash(interval_lo_us=400_000, interval_hi_us=1_200_000,
                          down_lo_us=300_000, down_hi_us=900_000),
            nemesis.Partition(interval_lo_us=500_000, interval_hi_us=1_500_000,
                              heal_lo_us=400_000, heal_hi_us=1_200_000),
        ),
    )
    # the pure-schedule face: open halves below the horizon
    want: dict = {}
    for ev in plan.schedule(SEED, HOR_US, N):
        if ev.kind in ("crash", "split", "clog", "spike_on") and ev.k >= 0:
            clause = nemesis.CLAUSE_OF_EVENT[ev.kind]
            want[clause] = want.get(clause, 0) | (1 << min(ev.k, 31))
    assert want.get("crash") and want.get("partition")

    # host face
    async def host_body():
        handle = ms.Handle.current()
        rafts = [RaftNode(i, N, [f"10.0.2.{j + 1}:6000" for j in range(N)])
                 for i in range(N)]
        nodes = [
            handle.create_node().name(f"raft-{i}").ip(f"10.0.2.{i + 1}")
            .init(lambda i=i: rafts[i].run()).build()
            for i in range(N)
        ]
        driver = nemesis.NemesisDriver(
            plan, handle, [nd.id for nd in nodes], horizon_us=HOR_US,
        )
        driver.install()
        t = ms.time.current()
        end = t.elapsed() + HOR_US / 1e6
        while t.elapsed() < end:
            await ms.time.sleep(0.02)
        return driver

    rt = ms.Runtime(seed=SEED)
    rt.block_on(host_body())
    assert rt.handle.metrics().chaos_occ_fired() == want

    # device face: the lane's occ_fired tensor for the same seed
    import jax.numpy as jnp

    from madsim_tpu.tpu import BatchedSim, SimConfig, make_raft_spec, summarize
    from madsim_tpu.tpu import nemesis as tpu_nemesis

    cfg = tpu_nemesis.compile_plan(plan, SimConfig(horizon_us=HOR_US))
    sim = BatchedSim(make_raft_spec(N), cfg)
    st = sim.run(jnp.asarray([SEED], jnp.uint32), max_steps=40_000)
    occ = np.asarray(st.occ_fired, np.uint32)[0]
    got = {
        c: int(occ[OCC_ROW[c]]) for c in OCC_CLAUSES if occ[OCC_ROW[c]]
    }
    assert got == want
    # and the summary keys render the same masks
    s = summarize(st)
    for clause, mask in want.items():
        for k in range(32):
            expect = 1 if (mask >> k) & 1 else 0
            assert s.get(f"occfires_{clause}_k{k}", 0) == expect


@pytest.mark.chaos
def test_lineage_three_face_twin_on_chaotic_raft_plan():
    """The causal-lineage twin (r12, docs/causality.md), three faces on
    one chaotic raft plan:

      device:  in-jit Lamport clocks / eids / sent_eid stamps, traced;
      mirror:  causal.graph_from_trace rebuilds the edge list and
               recomputes every Lamport clock purely from the edges —
               bit-equal to the in-jit values (enforced inside
               graph_from_trace; the coverage-twin discipline);
      host:    the host runtime's HostLineage mirror over the SAME plan
               records its own send/deliver events and edges, validated
               by the SAME Lamport law checker (causal.check_host_lineage).

    Unlike the chaos-STREAM twins above, device and host edges are not
    compared event-for-event: the backends roll their own network
    latencies (schedule-matched host replay and its divergence oracle
    live in madsim_tpu/oracle.py), so the trajectories — and therefore
    the delivery sets — differ by design. What all three faces share, and
    what this test pins, is the lineage law with one sender-value
    vocabulary: a message carries its send EVENT's id, and delivery
    updates max(local, sender) + 1."""
    import madsim_tpu as ms
    import numpy as np
    from madsim_tpu import causal, nemesis
    from madsim_tpu.workloads.raft_host import RaftNode

    N, SEED, HOR_US = 5, 5, 3_000_000
    plan = nemesis.FaultPlan(
        name="lineage-twin",
        clauses=(
            nemesis.Crash(interval_lo_us=400_000, interval_hi_us=1_200_000,
                          down_lo_us=300_000, down_hi_us=900_000),
            nemesis.Partition(interval_lo_us=500_000, interval_hi_us=1_500_000,
                              heal_lo_us=400_000, heal_hi_us=1_200_000),
        ),
    )

    # -- device face + pure mirror --------------------------------------
    from madsim_tpu.tpu import BatchedSim, SimConfig, make_raft_spec
    from madsim_tpu.tpu import nemesis as tpu_nemesis

    cfg = tpu_nemesis.compile_plan(plan, SimConfig(horizon_us=HOR_US))
    spec = make_raft_spec(N)
    sim = BatchedSim(spec, cfg, lineage=True)
    st, recs = sim.run_traced(SEED, max_steps=4_000)
    # graph_from_trace VERIFIES the mirror faces internally: every stamp
    # resolves to a real send event, in-jit lam == pure recomputation
    g = causal.graph_from_trace(
        recs, kind_names=spec.msg_kind_names, n_nodes=N,
    )
    assert len(g.msg_pred) > 20, "a chaotic raft lane must decode edges"
    assert len(g.events) == int(np.asarray(st.lin.eid)[0])

    # -- host face -------------------------------------------------------
    async def host_body():
        handle = ms.Handle.current()
        # opt-in, like the device plane: enable BEFORE traffic starts
        handle.metrics().lineage().enable()
        addrs = [f"10.0.3.{i + 1}:6000" for i in range(N)]
        rafts = [RaftNode(i, N, addrs) for i in range(N)]
        nodes = [
            handle.create_node().name(f"raft-{i}").ip(f"10.0.3.{i + 1}")
            .init(lambda i=i: rafts[i].run()).build()
            for i in range(N)
        ]
        driver = nemesis.NemesisDriver(
            plan, handle, [nd.id for nd in nodes], horizon_us=HOR_US,
        )
        driver.install()
        t = ms.time.current()
        end = t.elapsed() + HOR_US / 1e6
        while t.elapsed() < end:
            await ms.time.sleep(0.02)
        return handle.metrics().lineage()

    rt = ms.Runtime(seed=SEED)
    lineage = rt.block_on(host_body())
    assert lineage is not None
    assert len(lineage.edges) > 20, "host raft traffic must record edges"
    assert lineage.dropped == 0
    checked = causal.check_host_lineage(lineage)
    assert checked == len(lineage.edges)
    # the per-node clocks the mirror carries match its own event rows
    # (lam survives node resets: observer metadata, not node state)
    last_lam = {}
    for eid, node, lam_after, _kind in lineage.events:
        last_lam[node] = lam_after
    assert lineage.lam == last_lam


@pytest.mark.chaos
def test_reconfig_three_face_twin_schedule_host_device():
    """The r17 membership axis on all three faces: ONE FaultPlan with a
    `reconfig` clause + ONE seed gives the SAME remove/join stream on

      schedule: plan.schedule(seed, ...) — the pure murmur3 derivation;
      host:     NemesisDriver.applied (kill -> wipe -> restart with a
                fresh incarnation) plus its occ_fired["reconfig"] mask;
      device:   the traced engine's remove/join events and the lane's
                occ_fired tensor row.
    """
    import madsim_tpu as ms
    import numpy as np
    from madsim_tpu import nemesis
    from madsim_tpu.nemesis import OCC_ROW
    from madsim_tpu.workloads.raft_host import RaftNode

    N, SEED, HOR_US = 5, 5, 3_000_000
    plan = nemesis.FaultPlan(
        name="reconfig-twin",
        clauses=(
            nemesis.Crash(interval_lo_us=400_000, interval_hi_us=1_200_000,
                          down_lo_us=300_000, down_hi_us=900_000),
            nemesis.Reconfig(interval_lo_us=500_000, interval_hi_us=1_200_000,
                             down_lo_us=200_000, down_hi_us=600_000),
        ),
    )
    sched = plan.schedule(SEED, HOR_US, N)
    removes = [e for e in sched if e.kind == "remove"]
    joins = [e for e in sched if e.kind == "join"]
    assert removes and joins, "the reconfig clause must fire in-horizon"
    want_occ = 0
    for ev in removes:
        want_occ |= 1 << min(ev.k, 31)

    # -- host face: fresh-incarnation init closures under the driver ----
    async def host_body():
        handle = ms.Handle.current()
        addrs = [f"10.0.4.{i + 1}:6000" for i in range(N)]

        def mk(i):
            # a (re)start constructs a FRESH RaftNode: the join half of a
            # reconfig occurrence rebuilds from init state, the device
            # engine's `_v_init` twin
            return lambda: RaftNode(i, N, addrs).run()

        nodes = [
            handle.create_node().name(f"raft-{i}").ip(f"10.0.4.{i + 1}")
            .init(mk(i)).build()
            for i in range(N)
        ]
        driver = nemesis.NemesisDriver(
            plan, handle, [nd.id for nd in nodes], horizon_us=HOR_US,
        )
        driver.install()
        t = ms.time.current()
        end = t.elapsed() + HOR_US / 1e6
        while t.elapsed() < end:
            await ms.time.sleep(0.02)
        return driver

    rt = ms.Runtime(seed=SEED)
    driver = rt.block_on(host_body())
    assert driver.applied == [e for e in sched if e.kind != "skew"]
    assert driver.occ_fired.get("reconfig", 0) == want_occ

    # -- device face: same plan compiled onto the batched engine --------
    import jax.numpy as jnp

    from madsim_tpu.tpu import BatchedSim, SimConfig, make_raft_spec
    from madsim_tpu.tpu import nemesis as tpu_nemesis

    cfg = tpu_nemesis.compile_plan(plan, SimConfig(horizon_us=HOR_US))
    sim = BatchedSim(make_raft_spec(N), cfg)
    n_events = tpu_nemesis.assert_device_matches_schedule(
        sim, plan, SEED, horizon_us=HOR_US
    )
    assert n_events >= len(removes) + len(joins)
    st = sim.run(jnp.asarray([SEED], jnp.uint32), max_steps=40_000)
    occ = np.asarray(st.occ_fired, np.uint32)[0]
    assert int(occ[OCC_ROW["reconfig"]]) == want_occ


@pytest.mark.chaos
def test_reconfig_clause_fires_across_1024_seeds():
    """The membership axis is not a lottery ticket: across 1024 seeds of
    the planted-bug reconfig plan, EVERY pure schedule carries at least
    one in-horizon remove, and on a 1024-lane device sweep every lane's
    occ_fired row marks the clause (the engine applied what the schedule
    promised)."""
    import jax.numpy as jnp
    import numpy as np

    from madsim_tpu import nemesis
    from madsim_tpu.nemesis import OCC_ROW
    from madsim_tpu.tpu import BatchedSim
    from madsim_tpu.tpu.isr import isr_workload

    wl = isr_workload(virtual_secs=4.0)
    from madsim_tpu.triage import plan_from_config

    plan = nemesis.FaultPlan(
        name="sweep",
        clauses=tuple(
            c for c in plan_from_config(wl.config).clauses
            if isinstance(c, nemesis.Reconfig)
        ),
    )
    hor = int(wl.config.horizon_us)
    for seed in range(1024):
        evs = plan.schedule(seed, hor, wl.spec.n_nodes)
        assert any(e.kind == "remove" for e in evs), (
            f"seed {seed}: no reconfig occurrence below the horizon"
        )

    sim = BatchedSim(wl.spec, wl.config)
    st = sim.run(jnp.arange(1024, dtype=jnp.uint32), max_steps=25_000)
    occ = np.asarray(st.occ_fired, np.uint32)[:, OCC_ROW["reconfig"]]
    assert (occ != 0).all(), (
        f"{int((occ == 0).sum())} of 1024 lanes never applied a reconfig "
        "occurrence the schedule promised"
    )


def test_workloads_wire_host_repro():
    """All four protocols are debuggable from a violating seed: the
    workload factories ship a host_repro (VERDICT r4: twopc and paxos
    shipped host_repro=None)."""
    from madsim_tpu.tpu import raft_workload
    from madsim_tpu.tpu.kv import kv_workload
    from madsim_tpu.tpu.paxos import paxos_workload
    from madsim_tpu.tpu.twopc import twopc_workload
    from madsim_tpu.tpu.wal import wal_workload

    for wl in (
        raft_workload(), kv_workload(), twopc_workload(), paxos_workload(),
        wal_workload(),
    ):
        assert wl.host_repro is not None

    # and the repro runs end to end for the r5 twins (clean seed)
    out = twopc_workload(virtual_secs=4.0).host_repro(5)
    assert out["violations"] == 0
    out = paxos_workload(virtual_secs=4.0).host_repro(5)
    assert out["violations"] == 0
    # r18: the WAL twin drives real fs.File appends + power_fail recovery
    out = wal_workload(virtual_secs=4.0).host_repro(1)
    assert out["violations"] == 0


# -- r18: the durability axis (DiskFault) ------------------------------


def test_wal_host_twin_clean():
    """The correct fsync-before-ack WAL survives native disk chaos (slow
    disk -> power_fail with a torn tail -> recovery from the file)."""
    from madsim_tpu.workloads import wal_host

    r = wal_host.fuzz_one_seed(1, virtual_secs=6.0, buggy=False, disk=True)
    assert r["max_acked"] > 0
    assert r["final_log_len"] >= 0  # server recovered a parsable WAL


def test_wal_planted_bug_reproduces_on_both_faces():
    """ack-before-fsync loses acknowledged appends on BOTH faces once the
    durability axis is on (host: seed swept 0..7 -> 0,2..7 all hit)."""
    from madsim_tpu.workloads import wal_host

    with pytest.raises(wal_host.InvariantViolation, match="lost ack"):
        wal_host.fuzz_one_seed(0, virtual_secs=8.0, buggy=True, disk=True)

    import jax.numpy as jnp

    from madsim_tpu.tpu import BatchedSim, summarize
    from madsim_tpu.tpu.wal import wal_workload

    wl = wal_workload(virtual_secs=8.0, buggy=True)
    sim = BatchedSim(wl.spec, wl.config)
    state = sim.run(jnp.arange(192), max_steps=40_000)
    s = summarize(state)
    assert s["violations"] > 0
    # the lost-unsynced-state cold counter is the clause's own witness:
    # bug lanes lost bytes a quiet disk would have kept
    import numpy as np

    assert int(np.asarray(state.unsynced_loss).sum()) > 0


def test_wal_quiet_disk_control_is_silent():
    """CONTROL LEG: the SAME planted bug with the DiskFault clause absent
    is invisible — exactly zero violations on both faces. Ack-before-fsync
    only matters when unsynced state can actually be lost."""
    import jax.numpy as jnp
    import numpy as np

    from madsim_tpu.tpu import BatchedSim, summarize
    from madsim_tpu.tpu.wal import wal_workload
    from madsim_tpu.workloads import wal_host

    wl = wal_workload(virtual_secs=8.0, buggy=True, disk=False)
    sim = BatchedSim(wl.spec, wl.config)
    state = sim.run(jnp.arange(192), max_steps=40_000)
    assert summarize(state)["violations"] == 0
    assert int(np.asarray(state.unsynced_loss).sum()) == 0

    for seed in range(4):  # host leg of the same control
        r = wal_host.fuzz_one_seed(
            seed, virtual_secs=6.0, buggy=True, disk=False
        )
        assert r["max_acked"] > 0


@pytest.mark.chaos
def test_disk_three_face_twin_schedule_host_device():
    """The r18 durability axis on all three faces: ONE FaultPlan with a
    `disk` clause + ONE seed gives the SAME slow/crash/recover stream on

      schedule: plan.schedule(seed, ...) — the pure murmur3 derivation
                (episode phases share a victim; the torn coin rides both
                the crash and the recover);
      host:     NemesisDriver.applied (set_disk_fault -> kill +
                power_fail_node -> restart) over REAL fs.File WAL nodes,
                plus its occ_fired["disk"] mask;
      device:   the traced engine's disk events and the lane's occ_fired
                tensor row.
    """
    import numpy as np

    from madsim_tpu import nemesis
    from madsim_tpu.nemesis import OCC_ROW
    from madsim_tpu.workloads import wal_host

    N, SEED, HOR_US = 4, 5, 3_000_000
    plan = nemesis.FaultPlan(
        name="disk-twin",
        clauses=(
            nemesis.DiskFault(
                interval_lo_us=300_000, interval_hi_us=900_000,
                slow_lo_us=80_000, slow_hi_us=250_000,
                down_lo_us=200_000, down_hi_us=600_000,
                torn_rate=0.5, extra_us=30_000,
            ),
        ),
    )
    sched = plan.schedule(SEED, HOR_US, N)
    slows = [e for e in sched if e.kind == "disk_slow"]
    assert len(slows) >= 2, "the disk clause must fire in-horizon"
    episodes = {}
    for ev in sched:
        episodes.setdefault(ev.k, []).append(ev)
    order = ("disk_slow", "disk_crash", "disk_recover")
    for evs in episodes.values():
        # an episode keeps one victim through all its phases, in order,
        # and its crash and recover agree on the torn coin
        assert len({e.node for e in evs}) == 1
        kinds = tuple(e.kind for e in evs)
        assert kinds == order[: len(kinds)]
        assert len({e.torn for e in evs if e.kind != "disk_slow"}) <= 1
    want_occ = 0
    for ev in slows:
        want_occ |= 1 << min(ev.k, 31)

    # -- host face: the WAL twin's real files under the driver ----------
    r = wal_host.fuzz_one_seed(
        SEED, n_nodes=N, virtual_secs=HOR_US / 1e6, loss_rate=0.0,
        plan=plan,
    )
    bundle = r["nemesis"]
    assert bundle["applied"] == [e for e in sched if e.kind != "skew"]
    assert bundle["occ_fired"].get("disk", 0) == want_occ

    # -- device face: same plan compiled onto the batched engine --------
    import jax.numpy as jnp

    from madsim_tpu.tpu import BatchedSim, SimConfig
    from madsim_tpu.tpu import nemesis as tpu_nemesis
    from madsim_tpu.tpu.spec import pool_kw_for
    from madsim_tpu.tpu.wal import make_wal_spec

    spec = make_wal_spec(N)
    cfg = tpu_nemesis.compile_plan(
        plan,
        SimConfig(
            horizon_us=HOR_US,
            **pool_kw_for(
                spec,
                fused=dict(msg_depth_msg=2, msg_spare_slots=2),
                two_handler=dict(msg_depth_msg=2, msg_depth_timer=2),
            ),
        ),
    )
    sim = BatchedSim(spec, cfg)
    n_events = tpu_nemesis.assert_device_matches_schedule(
        sim, plan, SEED, horizon_us=HOR_US
    )
    assert n_events >= len(sched)
    st = sim.run(jnp.asarray([SEED], jnp.uint32), max_steps=40_000)
    occ = np.asarray(st.occ_fired, np.uint32)[0]
    assert int(occ[OCC_ROW["disk"]]) == want_occ


@pytest.mark.chaos
def test_disk_clause_fires_across_1024_seeds():
    """The durability axis is not a lottery ticket: across 1024 seeds of
    the wal workload's DiskFault plan, EVERY pure schedule opens at least
    one in-horizon episode, and on a 1024-lane device sweep every lane's
    occ_fired row marks the clause."""
    import jax.numpy as jnp
    import numpy as np

    from madsim_tpu import nemesis
    from madsim_tpu.nemesis import OCC_ROW
    from madsim_tpu.tpu import BatchedSim
    from madsim_tpu.tpu.wal import wal_workload
    from madsim_tpu.triage import plan_from_config

    wl = wal_workload(virtual_secs=4.0)
    plan = nemesis.FaultPlan(
        name="sweep",
        clauses=tuple(
            c for c in plan_from_config(wl.config).clauses
            if isinstance(c, nemesis.DiskFault)
        ),
    )
    assert plan.clauses, "the wal workload must carry a DiskFault clause"
    hor = int(wl.config.horizon_us)
    for seed in range(1024):
        evs = plan.schedule(seed, hor, wl.spec.n_nodes)
        assert any(e.kind == "disk_slow" for e in evs), (
            f"seed {seed}: no disk episode below the horizon"
        )

    sim = BatchedSim(wl.spec, wl.config)
    st = sim.run(jnp.arange(1024, dtype=jnp.uint32), max_steps=25_000)
    occ = np.asarray(st.occ_fired, np.uint32)[:, OCC_ROW["disk"]]
    assert (occ != 0).all(), (
        f"{int((occ == 0).sum())} of 1024 lanes never applied a disk "
        "episode the schedule promised"
    )


# ----------------------------------------------- speclang generated twins
#
# The twins below are not hand-written: madsim_tpu/speclang emits them
# from the same spec source as the device face (the generic hostrt twin
# runs the compiled handler bodies verbatim over the host runtime), so
# these tests pin the BOTH-faces contract for generated protocols too.


def test_backup_generated_host_twin_clean():
    """The speclang-native primary-backup protocol's generated host twin
    runs clean under host-native kill/restart/wipe chaos — same oracle
    (the spec's check_invariants) as the device face."""
    from madsim_tpu.speclang.generated import backup_host

    r = backup_host.fuzz_one_seed(3, virtual_secs=6.0)
    assert r["checks"] > 0
    assert r["events"] > 0


def test_backup_planted_bug_reproduces_on_host_face():
    """The stale-read bug lives on the duplicate/reorder axis, and the
    host face carries that axis through NemesisDriver plan mode — the
    SAME generated twin violates at a pinned seed (0; seeds 2,4,5,6,7
    also hit) once the plan arms Duplicate + Reorder."""
    from madsim_tpu import nemesis
    from madsim_tpu.speclang.generated import backup_host

    plan = nemesis.FaultPlan(
        name="backup-bug",
        clauses=(
            nemesis.Duplicate(rate=0.15),
            nemesis.Reorder(rate=0.3, window_us=250_000),
        ),
    )
    with pytest.raises(backup_host.InvariantViolation):
        backup_host.fuzz_one_seed(
            0, virtual_secs=8.0, chaos=False, plan=plan, buggy=True
        )
    # the correct build survives the identical plan and seed
    r = backup_host.fuzz_one_seed(
        0, virtual_secs=8.0, chaos=False, plan=plan
    )
    assert r["checks"] > 0


def test_lease_generated_host_twin_clean():
    """The lease re-derivation's generated twin (two-handler spec source
    fused by the compiler) holds its own invariant on the host face."""
    from madsim_tpu.speclang.generated import lease_host

    r = lease_host.fuzz_one_seed(1, virtual_secs=6.0)
    assert r["checks"] > 0
    assert r["events"] > 0
