"""Causal explainability (r12, docs/causality.md): in-jit happens-before
lineage, violation cone slicing, cross-witness bug anatomy.

The contracts under test:

  * OBSERVE-ONLY — every non-lineage output is bit-identical with
    lineage on/off, on the donated, refill, and sharded paths (same bar
    coverage=True met in r7); golden digests live in
    test_state_layout.py, layout/zero-bytes-off pins too.
  * EXACT DECODE — the u16 sent_eid stamps reconstruct to real send
    events (verified, never trusted), and the in-jit Lamport clocks
    equal the pure edge recomputation (the coverage-mirror discipline).
  * EXPLANATION — the planted deposed-leader re-stamp bug's causal
    slice names the re-stamp delivery chain, and >= 2 witnesses of the
    deduped bug share one event skeleton (seed-local noise aligned out).
"""

import dataclasses
import json
import os
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu import causal, nemesis
from madsim_tpu.tpu import make_raft_spec
from madsim_tpu.tpu import nemesis as tpu_nemesis
from madsim_tpu.tpu.engine import (
    BatchedSim,
    refill_results,
    refill_results_sharded,
    summarize,
)
from madsim_tpu.tpu.spec import SimConfig

CHAOS_PLAN = nemesis.FaultPlan(
    name="causal-chaos",
    clauses=(
        nemesis.Crash(interval_lo_us=300_000, interval_hi_us=900_000,
                      down_lo_us=200_000, down_hi_us=600_000),
        nemesis.Partition(interval_lo_us=400_000, interval_hi_us=1_200_000,
                          heal_lo_us=300_000, heal_hi_us=900_000),
        nemesis.MsgLoss(rate=0.05),
    ),
)


def chaotic_cfg(horizon_us=2_000_000):
    return tpu_nemesis.compile_plan(
        CHAOS_PLAN, SimConfig(horizon_us=horizon_us)
    )


def strip_lineage(state):
    """Drop the lineage plane so the remaining pytree can be compared
    leaf-for-leaf against a lineage-off state."""
    msgs = state.msgs._replace(sent_eid=None)
    strag = state.strag
    if strag is not None:
        strag = strag._replace(sent_eid=None)
    return state._replace(lin=None, msgs=msgs, strag=strag)


def assert_trees_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ------------------------------------------------- on/off bit-identity


@pytest.mark.chaos
def test_lineage_on_off_bit_identity_donated():
    """The acceptance bar: a chaotic sweep's every non-lineage leaf —
    summaries, coverage, chaos fires included — is bit-identical with
    lineage on, on the donated (default) path."""
    spec, cfg = make_raft_spec(), chaotic_cfg()
    seeds = jnp.arange(16, dtype=jnp.uint32)
    off = BatchedSim(spec, cfg, coverage=True).run(seeds, max_steps=1200)
    on = BatchedSim(spec, cfg, coverage=True, lineage=True).run(
        seeds, max_steps=1200
    )
    assert_trees_equal(off, strip_lineage(on))
    assert summarize(off) == summarize(strip_lineage(on))


@pytest.mark.chaos
def test_lineage_on_off_bit_identity_refill():
    """Same bar on the continuously batched path: per-admission rows
    (violations, steps, fires, occ_fired, coverage bitmaps) unchanged."""
    spec, cfg = make_raft_spec(), chaotic_cfg(horizon_us=600_000)
    seeds = np.arange(9, dtype=np.uint32)
    rows = []
    for lineage in (False, True):
        sim = BatchedSim(spec, cfg, coverage=True, lineage=lineage)
        st = sim.run_refill(seeds, lanes=4, max_steps=4_000)
        rows.append(refill_results(st))
    a, b = rows
    for key in ("violated", "violation_step", "steps", "events", "fires",
                "occ_fired", "cov_bitmap", "overflow", "dead_drops",
                "clock", "epoch", "retired"):
        np.testing.assert_array_equal(a[key], b[key], err_msg=key)


@pytest.mark.chaos
def test_lineage_on_off_bit_identity_sharded():
    """And on the multi-chip shard_map'd path (virtual mesh)."""
    spec, cfg = make_raft_spec(), chaotic_cfg(horizon_us=600_000)
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("devices",))
    seeds = np.arange(10, dtype=np.uint32)
    rows = []
    for lineage in (False, True):
        sim = BatchedSim(spec, cfg, lineage=lineage)
        st = sim.run_refill_sharded(seeds, lanes=3, mesh=mesh,
                                    max_steps=4_000)
        rows.append(refill_results_sharded(st, admissions=len(seeds)))
    a, b = rows
    for key in ("violated", "violation_step", "steps", "events", "fires",
                "occ_fired"):
        np.testing.assert_array_equal(a[key], b[key], err_msg=key)


# ------------------------------------------------- decode + verification


@pytest.mark.chaos
def test_graph_decode_and_lamport_mirror():
    """graph_from_trace VERIFIES the lineage plane: every sent_eid stamp
    resolves to a real earlier event at the recorded source node (the
    u16 rolling-window reconstruction, checked not trusted), and the
    in-jit Lamport clocks equal the pure edge recomputation."""
    spec, cfg = make_raft_spec(), chaotic_cfg()
    sim = BatchedSim(spec, cfg, lineage=True)
    for seed in (0, 7):
        st, recs = sim.run_traced(seed, max_steps=900)
        g = causal.graph_from_trace(
            recs, kind_names=spec.msg_kind_names, n_nodes=spec.n_nodes,
        )
        assert len(g.events) > 50
        assert len(g.msg_pred) > 10  # real message edges decoded
        # eid counter == events processed; final per-node clocks match
        # the carried plane
        assert len(g.events) == int(np.asarray(st.lin.eid)[0])
        mirror = causal.lamport_mirror(g)
        final_lam = np.asarray(st.lin.lam)[0]
        for n in range(spec.n_nodes):
            node_evts = [e for e in g.events.values() if e.node == n]
            if node_evts:
                last = max(node_evts, key=lambda e: e.eid)
                assert mirror[last.eid] == int(final_lam[n])


def test_lineage_covers_two_handler_and_straggler_paths():
    """The stamp plumbing on the OTHER engine paths: the two-handler
    (per-candidate-ring) pack and the heavy-tail straggler side pool
    both carry sent_eid stamps that decode and verify."""
    spec = make_raft_spec()
    from madsim_tpu.tpu.spec import replace_handlers

    two = replace_handlers(
        spec, on_message=spec.on_message, on_timer=spec.on_timer,
    )
    assert two.on_event is None  # the per-candidate-ring pack path
    sim = BatchedSim(two, None, lineage=True)
    _, recs = sim.run_traced(3, max_steps=400)
    g = causal.graph_from_trace(recs, kind_names=spec.msg_kind_names,
                                n_nodes=spec.n_nodes)
    assert len(g.msg_pred) > 10

    cfg = SimConfig(horizon_us=3_000_000, buggify_delay_rate=0.05,
                    buggify_delay_lo_us=200_000,
                    buggify_delay_hi_us=800_000)
    sim2 = BatchedSim(make_raft_spec(), cfg, lineage=True)
    assert sim2._B > 0  # straggler side pool in the program
    _, recs2 = sim2.run_traced(5, max_steps=1200)
    g2 = causal.graph_from_trace(recs2, kind_names=spec.msg_kind_names,
                                 n_nodes=spec.n_nodes)
    assert len(g2.msg_pred) > 10


def test_graph_rejects_traces_without_lineage():
    spec, cfg = make_raft_spec(), chaotic_cfg()
    sim = BatchedSim(spec, cfg)
    _, recs = sim.run_traced(0, max_steps=200)
    with pytest.raises(causal.LineageError, match="lineage"):
        causal.graph_from_trace(recs)


def test_lamport_mirror_detects_desync():
    """The checker is not vacuous: a tampered Lamport value fails."""
    spec, cfg = make_raft_spec(), chaotic_cfg()
    sim = BatchedSim(spec, cfg, lineage=True)
    _, recs = sim.run_traced(0, max_steps=400)
    from madsim_tpu.tpu.trace import extract_trace

    events = extract_trace(recs, kind_names=spec.msg_kind_names)
    stamped = [e for e in events if e.eid >= 0]
    bad = dataclasses.replace(stamped[len(stamped) // 2],
                              lam=stamped[len(stamped) // 2].lam + 7)
    tampered = [
        bad if e is stamped[len(stamped) // 2] else e for e in events
    ]
    with pytest.raises(causal.LineageError, match="Lamport"):
        causal.graph_from_events(tampered, n_nodes=spec.n_nodes)


# ------------------------------------------------- cone + slice + anatomy


def restamp_workload():
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "benches"))
    try:
        from ttfb import restamp_workload as rw
    finally:
        sys.path.pop(0)
    return rw()


def _violating_seeds(wl, lanes=48):
    sim = BatchedSim(wl.spec, wl.config)
    st = sim.run(jnp.arange(lanes, dtype=jnp.uint32), max_steps=20_000)
    viol = np.nonzero(np.asarray(st.violated))[0]
    steps = np.asarray(st.violation_step)
    assert viol.size >= 2, "planted re-stamp must violate on many seeds"
    return [(int(s), int(steps[s])) for s in viol]


@pytest.mark.slow
@pytest.mark.chaos
def test_slice_names_restamp_delivery_chain():
    """The acceptance bar: the planted deposed-leader re-stamp config's
    causal slice contains the re-stamp delivery chain — the anchor is
    the APPEND delivery that exposed the corrupted committed prefix, and
    the chain walks back through the APPEND/APPEND_RESP traffic that
    carried the re-stamped entries."""
    wl = restamp_workload()
    seed, step = _violating_seeds(wl)[0]
    g, sl = causal.explain(wl.spec, wl.config, seed, max_steps=step + 2)
    assert g.violation is not None
    anchor = g.events[sl.anchor_eid]
    assert anchor.step == g.violation.step
    labels = causal.slice_labels(sl)
    appends = [l for l in labels if l.startswith("deliver:APPEND:")]
    assert anchor.kind == "deliver" and anchor.msg_name == "APPEND", (
        "the violating step's event must be the re-stamped APPEND "
        f"delivery, got {anchor}"
    )
    assert len(appends) >= 2, (
        f"slice must contain the APPEND delivery chain, got {labels[-10:]}"
    )
    # the slice is a chain cut from a (much) larger cone
    assert sl.cone_size >= len(sl.chain)
    assert sl.depth >= 1
    # renderers run over the real slice
    text = causal.format_slice(causal.causal_slice(g, max_len=10))
    assert "APPEND" in text and "eid=" in text
    doc = causal.slice_perfetto(sl)
    assert any(ev.get("ph") == "s" for ev in doc["traceEvents"])


@pytest.mark.slow
@pytest.mark.chaos
def test_cross_witness_skeleton_identical():
    """The acceptance bar: >= 2 witnesses of the deduped re-stamp bug
    share one skeleton — nonempty, containing the APPEND mechanism, a
    subsequence of EVERY witness's slice, and deterministic."""
    from madsim_tpu.campaign import BugRecord, bug_anatomy
    from madsim_tpu.explore import Candidate, canon_genome

    wl = restamp_workload()
    seeds = _violating_seeds(wl)[:2]
    witnesses = [
        {
            "seed": s,
            "candidate": list(canon_genome(Candidate(seed=s).key())),
            "dispatch": 0, "origin": "fresh", "cov_digest": None,
        }
        for s, _ in seeds
    ]
    record = BugRecord(
        signature="sig-test", spec_name=wl.spec.name,
        violation_kind="invariant", clause_profile=[], witnesses=witnesses,
        bundle_path=None, campaign="c-test", first_generation=0,
        coarse_keys=[],
    )
    anatomy = bug_anatomy(wl, record)
    skel = anatomy["skeleton"]
    assert skel, "witnesses of one bug class must share a skeleton"
    assert any(l.startswith("deliver:APPEND:") for l in skel)
    assert len(anatomy["witnesses"]) == 2

    def is_subseq(small, big):
        it = iter(big)
        return all(any(x == y for y in it) for x in small)

    for s, _ in seeds:
        g, sl = causal.explain(wl.spec, wl.config, s,
                               max_steps=int(wl.max_steps))
        assert is_subseq(skel, causal.slice_labels(sl)), (
            f"skeleton must be a subsequence of witness {s}'s slice"
        )
        assert anatomy["witnesses"][0]["noise"] >= 0
    # deterministic: recomputation yields the identical skeleton
    again = bug_anatomy(wl, record)
    assert again["skeleton"] == skel
    assert again["skeleton_sha"] == anatomy["skeleton_sha"]
    # BugRecord round-trips the anatomy (and old records read back)
    record.anatomy = anatomy
    back = BugRecord.from_dict(json.loads(json.dumps(record.to_dict())))
    assert back.anatomy["skeleton_sha"] == anatomy["skeleton_sha"]
    doc = record.to_dict()
    doc.pop("anatomy")
    assert BugRecord.from_dict(doc).anatomy is None


@pytest.mark.slow
@pytest.mark.chaos
def test_shrink_causal_digest_and_repro_explain(tmp_path):
    """Bundle schema v3 end to end: shrink the planted re-stamp with
    causal=True, get the optional causal digest, round-trip it through
    save/load, and `repro --explain` (library face) recomputes the slice
    and cross-checks the digest sha."""
    from madsim_tpu import repro, triage
    from madsim_tpu.tpu.batch import BatchWorkload

    wl = restamp_workload()
    seed, _ = _violating_seeds(wl)[0]
    sr = triage.shrink_seed(
        wl, seed, out_dir=str(tmp_path),
        spec_ref="tests.test_triage:planted_restamp_spec",
        causal=True,
    )
    b = sr.bundle
    assert b.format == "madsim-tpu-repro/3"
    assert b.causal is not None
    assert b.causal["labels"] and b.causal["sha"]
    assert b.causal["cone_size"] >= b.causal["chain_len"]
    loaded = triage.ReproBundle.load(sr.bundle_path)
    assert loaded.causal == b.causal
    lines = []
    rep = repro.replay_device(
        loaded, spec=wl.spec, repeats=1, explain=8, out=lines.append,
    )
    assert rep["causal"]["sha"] == b.causal["sha"]
    assert any("causal slice" in ln for ln in lines)
    del BatchWorkload


def test_bundle_v2_reads_back_without_causal():
    """Back-compat: a v2 bundle document (no causal field) loads, with
    the digest defaulted to None — old bundles replay unchanged."""
    from madsim_tpu.triage import ReproBundle

    doc = {
        "seed": 5, "spec_ref": None, "spec_kwargs": {}, "spec_name": "x",
        "n_nodes": 3, "config_toml": "", "config_hash": "h",
        "violation_kind": "invariant", "violation_step": 10,
        "violation_t_us": 1000, "dropped_clauses": [], "occ_off": {},
        "rate_scale": {}, "horizon_us": 100, "max_steps": 10,
        "plan": {"name": "p", "clauses": []}, "trace_tail": [],
        "format": "madsim-tpu-repro/2", "signature": "s",
    }
    b = ReproBundle.from_json(json.dumps(doc))
    assert b.causal is None and b.signature == "s"
    # and an unknown field still fails loudly
    doc["nonesuch"] = 1
    with pytest.raises(ValueError, match="unknown bundle fields"):
        ReproBundle.from_json(json.dumps(doc))


# ------------------------------------------------- renderers + telemetry


def test_shiviz_log_parses():
    spec, cfg = make_raft_spec(), chaotic_cfg()
    sim = BatchedSim(spec, cfg, lineage=True)
    _, recs = sim.run_traced(0, max_steps=300)
    g = causal.graph_from_trace(recs, kind_names=spec.msg_kind_names,
                                n_nodes=spec.n_nodes)
    log = causal.shiviz_log(g)
    lines = [ln for ln in log.split("\n") if ln]
    assert len(lines) == 2 * len(g.events)
    head = re.compile(r"^(node\d+) (\{.*\})$")
    vcs = causal.vector_clocks(g)
    for i in range(0, len(lines), 2):
        m = head.match(lines[i])
        assert m, lines[i]
        json.loads(m.group(2))  # valid vector-clock JSON
    # vector clocks are monotone along message edges
    for de, se in g.msg_pred.items():
        assert all(a >= b for a, b in zip(vcs[de], vcs[se]))
        assert vcs[de] != vcs[se]


def test_record_causal_histograms(tmp_path):
    import madsim_tpu.telemetry as telemetry

    reg = telemetry.enable(out_dir=str(tmp_path))
    try:
        telemetry.record_causal(
            {"depth": 12, "cone_size": 40, "chain_len": 7},
            workload="raft",
        )
        snap = reg.histogram("causal_depth").snapshot(workload="raft")
        assert snap and snap["count"] == 1 and snap["sum"] == 12
        snap = reg.histogram("causal_cone_width").snapshot(workload="raft")
        assert snap and snap["sum"] == 40
    finally:
        telemetry.disable()


# ------------------------------------------------------- lint satellite


def test_causal_module_passes_entropy_lint_without_pragmas():
    """causal.py is a pure decoder: the ambient-entropy rule passes with
    ZERO violations and the module carries no suppression pragma (the
    bar PR 11 set for telemetry.py)."""
    from madsim_tpu.analysis.lint import check_entropy_file, repo_root

    root = repo_root()
    path = os.path.join(root, "madsim_tpu", "causal.py")
    res = check_entropy_file(path, root)
    assert res.violations == [], res.violations
    assert res.checked > 0
    with open(path) as f:
        assert "madsim: allow" not in f.read()
