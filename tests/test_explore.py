"""Explorer: coverage-guided seed & fault-plan search (madsim_tpu/explore).

The subsystem's contract (docs/explore.md):
  * bit-determinism: the whole search is a pure function of ONE meta-seed
    — two runs (pipeline on or off, chunked or not) produce identical
    corpus contents, coverage curves and violation sets;
  * monotone coverage: the union bitmap only grows, and the corpus admits
    exactly the lanes that grew it;
  * violations arrive with ReproBundles — mutants shrink WITHIN their
    suppression set (triage.shrink_seed base_ctl), so the bundle replays
    the exact candidate that violated.

`chaos`-marked tests are the explore-smoke tier (`make explore-smoke`);
`slow`-marked sweeps run nightly.
"""

import dataclasses

import numpy as np
import pytest

from madsim_tpu import triage
from madsim_tpu.explore import (
    Candidate,
    Explorer,
    MetaRng,
    cov_index,
    payload_bucket,
    popcount_rows,
)
from madsim_tpu.nemesis import (
    Crash,
    FaultPlan,
    OCC_CLAUSES,
    OCC_ROW,
    Partition,
    TRIAGE_BIT,
)

HORIZON_US = 2_500_000

# the planted deposed-leader re-stamp bug under a schedule-clause plan
# (test_triage's configuration at a shorter horizon: the explorer needs
# real occurrence atoms to mutate, and the bug to find)
PLAN = FaultPlan(name="explore-test", clauses=(
    Crash(interval_lo_us=300_000, interval_hi_us=900_000,
          down_lo_us=200_000, down_hi_us=700_000),
    Partition(interval_lo_us=250_000, interval_hi_us=800_000,
              heal_lo_us=300_000, heal_hi_us=900_000),
))


def _planted_workload():
    from tests.test_triage import planted_restamp_spec

    from madsim_tpu.tpu import SimConfig, raft_workload
    from madsim_tpu.tpu import nemesis as tn

    cfg = tn.compile_plan(
        PLAN, SimConfig(horizon_us=HORIZON_US, loss_rate=0.0)
    )
    return dataclasses.replace(
        raft_workload(spec=planted_restamp_spec()), config=cfg,
        host_repro=None, max_steps=20_000,
    )


# ------------------------------------------------------------- pure pieces


def test_meta_rng_is_a_pure_counter_chain():
    a, b = MetaRng(7), MetaRng(7)
    assert [a.u32() for _ in range(8)] == [b.u32() for _ in range(8)]
    assert MetaRng(7).u32() != MetaRng(8).u32()
    r = MetaRng(3)
    assert all(0 <= r.randint(2, 9) < 9 for _ in range(32))
    assert r.randint(5, 5) == 5  # degenerate range, like prng.randint


def test_candidate_base_ctl_faces():
    assert Candidate(seed=3).base_ctl() is None
    occ = [0] * len(OCC_CLAUSES)
    occ[OCC_ROW["partition"]] = 0b101
    c = Candidate(
        seed=3, off=TRIAGE_BIT["loss"],
        occ_off=tuple(occ), rate_scale=(1.0, 0.5, 1.0),
        horizon_us=1_000_000,
    )
    ctl = c.base_ctl()
    assert ctl == {
        "off_clauses": ["loss"],
        "occ_off": {"partition": 0b101},
        "rate_scale": {"dup": 0.5},
        "horizon_us": 1_000_000,
    }
    assert "partition.occ_off=0x5" in c.describe()
    # genome identity excludes provenance
    assert c.key() == dataclasses.replace(c, origin="swarm").key()
    # corpus lines from before a registry grew pad to the current length
    old = Candidate.from_dict({"seed": 1, "occ_off": [0, 0b101, 0, 0]})
    assert len(old.occ_off) == len(OCC_CLAUSES)
    assert old.base_ctl()["occ_off"] == {"partition": 0b101}


def test_cov_index_mirrors_engine_hash_shape():
    from madsim_tpu.tpu.engine import COV_BITS

    seen = {cov_index(n, s, k, b)
            for n in range(5) for s in (-1, 0, 3)
            for k in (-1, 0, 2) for b in (0, 1, 17)}
    assert all(0 <= i < COV_BITS for i in seen)
    assert len(seen) > 60  # the hash spreads distinct event classes
    assert payload_bucket(0) == 0
    assert payload_bucket(1) == 1
    assert payload_bucket(-1) == 32  # i32 -1 reinterprets as u32 max
    assert popcount_rows(np.asarray([[0b1011, 0]], np.uint32)).tolist() == [3]


def test_explore_report_json_roundtrip_preserves_fingerprint():
    """The campaign checkpoint/service contract: a report reloaded from
    its JSON line fingerprints identically (tuple->list collapse is
    canonicalized away) and compares field-for-field."""
    from madsim_tpu.explore import ExploreReport

    rep = ExploreReport(
        meta_seed=11, lanes=16, dispatches=3,
        coverage_curve=[40, 61, 61], corpus_curve=[3, 5, 5],
        violation_curve=[0, 1, 2],
        violations=[{
            "candidate": (9, 2, (0, 0b101, 0, 0), (1.0, 0.5, 1.0), 0),
            "seed": 9, "origin": "mutant", "describe": "[mutant] seed=9",
            "dispatch": 1, "bundle_path": "/tmp/x.json",
            "cov_digest": "ab" * 32,
        }],
        coverage_bits=61, corpus_size=5, seeds_run=48,
        first_violation_dispatch=1, wall_s=1.25, device_dispatches=6,
        corpus_digest="feed" * 16,
    )
    again = ExploreReport.from_json(rep.to_json())
    assert again.fingerprint() == rep.fingerprint()
    # candidate genomes come back in the canonical in-memory tuple form
    assert again.violations == rep.violations
    assert again.to_dict()["coverage_curve"] == rep.coverage_curve
    # a second round trip is a fixed point
    assert ExploreReport.from_json(again.to_json()).fingerprint() == \
        rep.fingerprint()
    with pytest.raises(ValueError, match="unknown"):
        ExploreReport.from_dict({**rep.to_dict(), "bogus": 1})
    # MetaRng state face: (seed, counter) IS the whole state
    r = MetaRng(5)
    draws = [r.u32() for _ in range(6)]
    resumed = MetaRng(5, counter=4)
    assert resumed.counter == 4
    assert [resumed.u32(), resumed.u32()] == draws[4:]


def test_occurrence_fires_parses_summary_keys():
    from madsim_tpu.tpu.nemesis import occurrence_fires

    assert occurrence_fires({
        "occfires_crash_k0": 12, "occfires_crash_k2": 3,
        "occfires_partition_k0": 7, "fires_crash": 15,
    }) == {"crash": {0: 12, 2: 3}, "partition": {0: 7}}


# ------------------------------------------------------------ the search


@pytest.mark.chaos
def test_explorer_meta_seed_determinism_and_monotone_coverage():
    """The acceptance contract: identical meta-seed => identical corpus,
    curves and violation sets, pipelined or serial — and the coverage
    curve only grows."""
    wl = _planted_workload()
    reports = []
    for pipeline in (True, True, False):
        ex = Explorer(
            wl, meta_seed=11, lanes=16, chunk=8, shrink_violations=False,
            pipeline=pipeline,
        )
        reports.append((ex, ex.run(3)))
    (ex_a, a), (_, b), (_, c) = reports
    assert a.fingerprint() == b.fingerprint() == c.fingerprint()
    for x, y in ((a, b), (a, c)):
        assert x.coverage_curve == y.coverage_curve
        assert x.violations == y.violations
    corpora = [
        [(e.cand.key(), e.new_bits, e.bitmap.tobytes())
         for e in ex.corpus]
        for ex, _ in reports
    ]
    assert corpora[0] == corpora[1] == corpora[2]
    # monotone, non-trivial coverage; corpus admissions account for it
    assert a.coverage_curve == sorted(a.coverage_curve)
    assert a.coverage_bits > 0
    assert sum(e.new_bits for e in ex_a.corpus) == a.coverage_bits
    union = np.zeros_like(ex_a.union)
    for e in ex_a.corpus:
        union |= e.bitmap
    assert np.array_equal(union, ex_a.union)
    # generations past 0 actually steer (mutants/swarm in the population)
    origins = {e.cand.origin for e in ex_a.corpus}
    assert ex_a.seeds_run == 48
    assert ex_a._gen == 3
    del origins  # composition varies with novelty; pinned in the slow test


@pytest.mark.chaos
def test_explorer_surfaces_planted_bug_with_bundle(tmp_path):
    """Violations flow straight into triage: every surfaced violation
    carries a ReproBundle that replays its candidate."""
    wl = _planted_workload()
    ex = Explorer(
        wl, meta_seed=0, lanes=64, shrink_violations=True,
        max_shrinks=2,  # the planted bug is seed-dense; 2 bundles prove
        # the path without paying ~10 ddmin dispatches per violating lane
        shrink_kwargs={"out_dir": str(tmp_path)},
    )
    rep = ex.run(2)
    assert rep.violations, "planted bug not found in 128 lanes"
    assert rep.first_violation_dispatch == 0  # dispatch 0 == uniform chunk
    shrunk = [v for v in rep.violations if v.get("bundle_path")]
    assert len(shrunk) == min(2, len(rep.violations))
    for v in rep.violations[len(shrunk):]:
        assert v.get("shrink_skipped") == "max_shrinks reached"
    for v in shrunk:
        bundle = triage.ReproBundle.load(v["bundle_path"])
        assert bundle.seed == v["seed"]
        assert bundle.violation_step > 0


@pytest.mark.chaos
def test_shrink_seed_base_ctl_keeps_candidate_suppressions():
    """shrink_seed(base_ctl=...) ddmins WITHIN the candidate: base
    suppressions stay suppressed in every evaluated row and land in the
    bundle's ctl, so the bundle replays the shrunk candidate exactly."""
    wl = _planted_workload()
    # find a violating seed + its plain shrink first
    from madsim_tpu.tpu.batch import run_batch

    res = run_batch(
        range(64), wl, mesh=None, max_traces=0, repro_on_host=False,
    )
    assert res.violations
    seed = res.violating_seeds[0]
    plain = triage.shrink_seed(wl, seed)
    dropped_occ = {
        name: mask for name, mask in plain.bundle.occ_off.items()
    }
    if dropped_occ:
        # suppress an occurrence the plain shrink already dropped: the
        # violation must survive, and the suppression must stay in the
        # bundle's ctl (the merge path)
        name = sorted(dropped_occ)[0]
        bit = dropped_occ[name] & -dropped_occ[name]  # lowest dropped bit
        based = triage.shrink_seed(
            wl, seed, base_ctl={"occ_off": {name: int(bit)}},
        )
        assert based.bundle.occ_off.get(name, 0) & bit or (
            name in based.bundle.dropped_clauses
        )
        # the based shrink's kept set never resurrects the suppressed atom
        k = int(bit).bit_length() - 1
        assert (name, k) not in based.kept_atoms
        assert based.bundle.violation_step > 0
    else:
        # the plain shrink's kept set is 1-minimal over its vocabulary:
        # every kept atom is load-bearing at the truncated horizon.
        # Suppressing one via base_ctl either makes the candidate stop
        # violating (NotReproducible — the baseline honored the
        # suppression) or, if later windows at the full horizon still
        # break the invariant, yields a bundle whose ctl carries the
        # suppression and whose kept set never resurrects the atom.
        assert plain.kept_atoms, "shrink kept nothing yet violated?"
        name, k = plain.kept_atoms[-1]
        ctl = (
            {"occ_off": {name: 1 << k}} if k is not None
            else {"off_clauses": [name]}
        )
        try:
            based = triage.shrink_seed(wl, seed, base_ctl=ctl)
        except triage.NotReproducible:
            pass  # suppression honored: the candidate no longer violates
        else:
            assert (name, k) not in based.kept_atoms
            if k is not None:
                assert based.bundle.occ_off.get(name, 0) & (1 << k) or (
                    name in based.bundle.dropped_clauses
                )
            else:
                assert name in based.bundle.dropped_clauses
            assert based.bundle.violation_step > 0


@pytest.mark.slow
@pytest.mark.chaos
def test_explorer_beats_or_matches_uniform_on_planted_bug():
    """The bench acceptance in miniature: on the planted config the
    explorer reaches its first violation in no more dispatches than a
    uniform sweep of the same lane budget (generation 0 IS the uniform
    sweep's first chunk, so it can never do worse when the bug is
    first-chunk-dense; later generations steer)."""
    from madsim_tpu.tpu.batch import run_batch

    wl = _planted_workload()
    lanes, max_d = 64, 6
    uniform_first = None
    for d in range(max_d):
        r = run_batch(
            range(d * lanes, (d + 1) * lanes), wl, mesh=None,
            max_traces=0, repro_on_host=False,
        )
        if r.violations:
            uniform_first = d
            break
    ex = Explorer(wl, meta_seed=0, lanes=lanes, shrink_violations=False)
    rep = ex.run(max_d)
    assert rep.first_violation_dispatch is not None
    assert uniform_first is not None
    assert rep.first_violation_dispatch <= uniform_first
    # and steering is active: post-gen-0 populations carry mutants
    origins = {e.cand.origin for e in ex.corpus if e.dispatch > 0}
    assert origins & {"mutant", "swarm", "fresh"}
