"""Tracing tests: log records emitted inside tasks carry node/task/virtual
time automatically (the reference's per-node tracing spans,
task/mod.rs:119-441)."""

import io
import logging

import madsim_tpu as ms


def test_logs_attributed_to_node_and_task():
    buf = io.StringIO()
    handler = ms.tracing.init_logger(logging.INFO, stream=buf)
    try:
        rt = ms.Runtime(seed=1)
        log = logging.getLogger("test.tracing")

        async def main():
            h = rt.handle
            a = h.create_node().name("alpha").build()
            b = h.create_node().name("beta").build()

            async def worker(tag):
                await ms.time.sleep(1.0)
                log.info("hello from %s", tag)

            t1 = a.spawn(worker("a"))
            t2 = b.spawn(worker("b"))
            await t1
            await t2
            log.info("done")

        rt.block_on(main())
    finally:
        logging.getLogger().removeHandler(handler)

    out = buf.getvalue()
    lines = out.strip().splitlines()
    assert len(lines) == 3
    assert "node=1'alpha'" in lines[0] or "node=1'alpha'" in lines[1]
    assert "node=2'beta'" in out
    assert "hello from a" in out and "hello from b" in out
    # virtual timestamp present (1.0s sleep happened)
    assert "[1.00" in out
    # the final log came from the main node's root task
    assert "'main'" in lines[2]


def test_logs_outside_sim_unstamped():
    buf = io.StringIO()
    handler = ms.tracing.init_logger(logging.INFO, stream=buf)
    try:
        logging.getLogger("test.tracing").info("plain")
    finally:
        logging.getLogger().removeHandler(handler)
    out = buf.getvalue()
    assert "plain" in out
    assert "node=" not in out
