"""Donation + pipelining determinism, and the dispatch budget (r6).

The r6 perf work changed HOW sweeps execute — carry buffers are donated
across sweep segments, run_batch double-buffers its chunk loop, triage
overlaps ddmin generation chunks — while the CONTRACT is that none of it
may change a single bit of any result. These tests pin that contract, and
the dispatch budget pins the sweep's launch count so eager-init-style
regressions (the r5 ~1.4 s/sweep dispatch-storm bug) fail loudly.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu.tpu import BatchedSim, SimConfig, make_raft_spec, raft_workload
from madsim_tpu.tpu.batch import run_batch


def _leaves_equal(a, b) -> bool:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
    )


def _tiny_workload(virtual_secs: float = 0.6):
    wl = raft_workload(virtual_secs=virtual_secs)
    return dataclasses.replace(wl, max_steps=2_500, host_repro=None)


# ------------------------------------------------------------- donation


def test_donated_sweep_bit_identical_to_undonated():
    """The donated segment function must produce the exact state an
    undonated copy of the SAME body produces — donation is an aliasing
    hint, never a semantic one."""
    spec = make_raft_spec(5)
    cfg = SimConfig(
        horizon_us=400_000,
        loss_rate=0.1,
        crash_interval_lo_us=100_000,
        crash_interval_hi_us=300_000,
        partition_interval_lo_us=100_000,
        partition_interval_hi_us=300_000,
    )
    sim = BatchedSim(spec, cfg)
    seeds = jnp.arange(48)
    # an undonated jit of the same underlying body
    undonated = jax.jit(
        BatchedSim._run.__wrapped__, static_argnums=(0, 2)
    )
    ref = undonated(sim, sim.init(seeds), 600)
    out = sim._run(sim.init(seeds), 600)  # the donated production path
    assert _leaves_equal(ref, out)


def test_donated_run_end_to_end_deterministic():
    """Two full run() sweeps of the same seeds through the donated
    chunked path stay bit-identical (the donated buffers are never read
    after reuse)."""
    sim = BatchedSim(make_raft_spec(5), SimConfig(horizon_us=500_000))
    a = sim.run(jnp.arange(32), max_steps=1_500, dispatch_steps=400)
    b = sim.run(jnp.arange(32), max_steps=1_500, dispatch_steps=400)
    assert _leaves_equal(a, b)


# ----------------------------------------------------------- pipelining


def _strip_timing(summary):
    return {k: v for k, v in summary.items() if k != "device_ms"}


def test_pipelined_run_batch_bit_identical_to_serial():
    """Chunked sweeps, pipelined vs serial: identical violation lanes,
    identical final state, identical summaries (incl. chaos_fires) —
    pipelining only moves the host's READ order."""
    wl = _tiny_workload()
    kw = dict(chunk=16, mesh=None, max_traces=0, repro_on_host=False)
    piped = run_batch(range(48), wl, pipeline=True, **kw)
    serial = run_batch(range(48), wl, pipeline=False, **kw)
    assert np.array_equal(piped.violated, serial.violated)
    assert np.array_equal(piped.deadlocked, serial.deadlocked)
    assert piped.chaos_fires == serial.chaos_fires
    assert _strip_timing(piped.summary) == _strip_timing(serial.summary)
    assert _leaves_equal(piped.state, serial.state)


@pytest.mark.slow
def test_pipelined_run_batch_big_sweep_bit_identical():
    """The 1024-seed acceptance variant of the pipelining contract, with
    chaos on so violation lanes and chaos_fires are exercised for real."""
    wl = raft_workload(virtual_secs=3.0)
    wl = dataclasses.replace(wl, max_steps=6_000, host_repro=None)
    kw = dict(chunk=256, mesh=None, max_traces=0, repro_on_host=False)
    piped = run_batch(range(1024), wl, pipeline=True, **kw)
    serial = run_batch(range(1024), wl, pipeline=False, **kw)
    assert np.array_equal(piped.violated, serial.violated)
    assert piped.chaos_fires == serial.chaos_fires
    assert _strip_timing(piped.summary) == _strip_timing(serial.summary)
    assert _leaves_equal(piped.state, serial.state)


@pytest.mark.slow
def test_donated_sweep_big_bit_identity():
    """Big-sweep donation identity: the chunked donated path at several
    segments equals a fresh undonated execution, leaf for leaf."""
    spec = make_raft_spec(5)
    cfg = SimConfig(
        horizon_us=3_000_000,
        loss_rate=0.1,
        crash_interval_lo_us=400_000,
        crash_interval_hi_us=1_500_000,
        partition_interval_lo_us=300_000,
        partition_interval_hi_us=1_200_000,
    )
    sim = BatchedSim(spec, cfg)
    undonated = jax.jit(
        BatchedSim._run.__wrapped__, static_argnums=(0, 2)
    )
    ref = undonated(sim, sim.init(jnp.arange(256)), 4_000)
    out = sim.run(jnp.arange(256), max_steps=4_000, dispatch_steps=1_000)
    assert _leaves_equal(ref, out)


# -------------------------------------------------------- dispatch budget


def test_dispatch_budget_single_chunk():
    """One chunk, one segment: exactly TWO device program launches (jitted
    init + one while_loop segment). An eager init is dozens; a
    step-granular loop would be thousands — both blow this loudly."""
    wl = _tiny_workload()
    res = run_batch(
        range(64), wl, mesh=None, max_traces=0, repro_on_host=False
    )
    assert res.dispatches == 2, res.dispatches
    assert res.summary["dispatches"] == 2
    assert res.device_ms > 0


def test_dispatch_budget_chunked():
    """k chunks of one segment each: exactly 2k launches, and the budget
    scales with chunks, not with steps or lanes."""
    wl = _tiny_workload()
    res = run_batch(
        range(64), wl, chunk=16, mesh=None, max_traces=0,
        repro_on_host=False,
    )
    assert res.dispatches == 8, res.dispatches  # 4 chunks x (init + run)


def test_init_is_one_jitted_program():
    """The r5 regression shape: sweep init must be ONE compiled program,
    not eager per-op dispatches. jax.jit exposes .lower on the wrapper —
    an un-jitted init loses it (and the budget above catches the launch
    storm)."""
    sim = BatchedSim(make_raft_spec(5), SimConfig(horizon_us=200_000))
    assert hasattr(sim.init, "lower")
    assert hasattr(sim._run, "lower")
    before = sim.dispatch_count
    sim.run(jnp.arange(8), max_steps=200)
    assert sim.dispatch_count - before == 2


# ------------------------------------------------ coverage instrumentation


def test_coverage_bitmap_identical_across_repeats_and_pipeline():
    """The explorer's novelty signal must be bit-deterministic: the same
    seeds produce the same per-lane bitmaps, occurrence fires and scalar
    features on every run, chunked or not, pipelined or serial (the
    decode order never touches device results)."""
    wl = _tiny_workload()
    kw = dict(mesh=None, max_traces=0, repro_on_host=False, coverage=True)
    a = run_batch(range(48), wl, chunk=16, pipeline=True, **kw)
    b = run_batch(range(48), wl, chunk=16, pipeline=False, **kw)
    c = run_batch(range(48), wl, chunk=48, pipeline=True, **kw)
    for other in (b, c):
        assert np.array_equal(a.coverage.bitmap, other.coverage.bitmap)
        assert np.array_equal(a.coverage.hiwater, other.coverage.hiwater)
        assert np.array_equal(
            a.coverage.transitions, other.coverage.transitions
        )
        assert a.summary["coverage_bits"] == other.summary["coverage_bits"]
    assert a.coverage.bitmap.shape == (48, 256)
    assert a.summary["coverage_bits"] == a.coverage.union_bits() > 0
    # coverage off: no bitmap cost, no coverage field
    plain = run_batch(
        range(48), wl, chunk=48, mesh=None, max_traces=0,
        repro_on_host=False,
    )
    assert plain.coverage is None
    assert "coverage_bits" not in plain.summary


def test_coverage_on_donated_path_bit_identical():
    """Donation must not perturb the coverage accumulators: the donated
    segment function's Coverage leaves equal an undonated execution of
    the same body."""
    spec = make_raft_spec(5)
    cfg = SimConfig(horizon_us=400_000, loss_rate=0.1)
    sim = BatchedSim(spec, cfg, coverage=True)
    seeds = jnp.arange(32)
    undonated = jax.jit(
        BatchedSim._run.__wrapped__, static_argnums=(0, 2)
    )
    ref = undonated(sim, sim.init(seeds), 600)
    out = sim._run(sim.init(seeds), 600)
    assert _leaves_equal(ref.cov, out.cov)
    assert _leaves_equal(ref, out)


# ------------------------------------------------- twopc fused-path parity


def _twopc_parity_cfg():
    return SimConfig(
        horizon_us=2_000_000,
        msg_capacity=128,
        loss_rate=0.1,
        crash_interval_lo_us=400_000,
        crash_interval_hi_us=2_000_000,
        restart_delay_lo_us=200_000,
        restart_delay_hi_us=1_000_000,
        partition_interval_lo_us=400_000,
        partition_interval_hi_us=1_500_000,
        partition_heal_lo_us=300_000,
        partition_heal_hi_us=1_200_000,
    )


# sha256 over the final-state leaves (tree order) of the R5 per-kind
# twopc handlers (lax.switch h_prepare/h_vote/h_outcome/h_dreq +
# fuse_two_handlers) on _twopc_parity_cfg, seeds 0..31, 8k steps, CPU —
# captured from the pre-r6 module at the commit that replaced it. The
# r6 hand-fused on_event claims bit-identity with those handlers; this
# digest is the in-tree witness (the wrapper-vs-fused comparison below
# alone would be circular: both sides share the fused body).
#
# LAYOUT-VERSION r8 re-bless: this digest hashes the RAW at-rest leaves,
# so the r8 carry compaction (twopc narrow_fields i16/u8 storage +
# bit-packed valid planes) changed it with NO trajectory change. The
# trajectory-level equivalence old-layout == new-layout is pinned
# separately by tests/test_state_layout.py's canonical golden digests
# (twopc constant produced identically by the r7 and r8 engines), so the
# witness chain r5-handlers == r6-fused == r8-compacted is unbroken.
# Pre-r8 value: 3257fd77792c2139b2264c2f2c75776260c7cebe38add0aa783f674aa1fa46c6
_R5_TWOPC_DIGEST = (
    "294c54ac291e30ceddf114b09a5654893048edfe27bafe90189d0efb019713ac"
)


def _state_digest(state) -> str:
    import hashlib

    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(state):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


@pytest.mark.skipif(
    jax.default_backend() != "cpu",
    reason="golden digest captured on the CPU backend (per-backend "
    "determinism contract: trajectories are pinned per backend)",
)
def test_twopc_hand_fused_matches_r5_golden_trajectory():
    """The hand-fused twopc must reproduce the EXACT trajectory of the
    deleted r5 per-kind handlers — pinned by a digest captured from the
    old module, so a transcription error in the masked merge cannot
    hide behind a self-consistent wrong body."""
    from madsim_tpu.tpu.twopc import make_twopc_spec

    state = BatchedSim(make_twopc_spec(5), _twopc_parity_cfg()).run(
        jnp.arange(32), max_steps=8_000
    )
    assert _state_digest(state) == _R5_TWOPC_DIGEST


def test_twopc_hand_fused_matches_generic_fusion():
    """The hand-fused on_event must also equal the generic
    fuse_two_handlers wrapping of its own derived two-handler view (this
    pins the wrapper plumbing; the golden-digest test above pins the
    body itself against r5)."""
    from madsim_tpu.tpu.spec import fuse_two_handlers
    from madsim_tpu.tpu.twopc import make_twopc_spec

    cfg = _twopc_parity_cfg()
    hand = make_twopc_spec(5)
    generic = fuse_two_handlers(
        dataclasses.replace(hand, on_event=None)
    )
    a = BatchedSim(hand, cfg).run(jnp.arange(32), max_steps=8_000)
    b = BatchedSim(generic, cfg).run(jnp.arange(32), max_steps=8_000)
    assert _leaves_equal(a, b)
