"""Per-lane violation traces: the device-side repro microscope (VERDICT r3).

The reference's DX bar is exact repro from the printed seed
(runtime/mod.rs:194-199). These tests hold the batched engine to a higher
one: a violating seed re-runs ON DEVICE with full event capture, and the
captured trace alone — no host twin — is enough to see the bug mechanics.
"""

import dataclasses
import pytest

import jax.numpy as jnp
import numpy as np

from madsim_tpu.tpu.spec import replace_handlers
from madsim_tpu.tpu import (
    BatchedSim,
    BatchWorkload,
    SimConfig,
    make_raft_spec,
    run_batch,
    trace_seed,
)
from madsim_tpu.tpu import raft as raft_mod
from madsim_tpu.tpu.trace import extract_trace, format_trace


def partition_config(**kw):
    defaults = dict(
        horizon_us=8_000_000,
        loss_rate=0.05,
        partition_interval_lo_us=300_000,
        partition_interval_hi_us=1_500_000,
        partition_heal_lo_us=500_000,
        partition_heal_hi_us=2_000_000,
    )
    defaults.update(kw)
    return SimConfig(**defaults)


def split_brain_spec():
    """The injected bug: a leader commits on ANY single ack (no majority).
    Fatal only under partitions — a minority-side leader keeps committing
    while the majority elects a new leader and commits different entries."""
    spec = make_raft_spec(5, client_rate=0.8)

    def buggy_append_resp(s, nid, src, kind, payload, now, key):
        state, out, timer = spec.on_message(s, nid, src, kind, payload, now, key)
        is_ar = kind == raft_mod.APPEND_RESP
        success = payload[1] > 0
        match = payload[2]
        bogus_commit = jnp.where(
            is_ar & success & (state.role == raft_mod.LEADER),
            jnp.maximum(state.commit, jnp.minimum(match, state.log_len - 1)),
            state.commit,
        )
        return state._replace(commit=bogus_commit), out, timer

    return replace_handlers(spec, on_message=buggy_append_resp)


@pytest.mark.deep
def test_trace_matches_batch_lane_bitwise():
    # the traced single-lane rerun is the SAME trajectory as the batch lane:
    # seeds, not lane positions, drive all randomness
    sim = BatchedSim(make_raft_spec(5), partition_config(horizon_us=2_000_000))
    batch = sim.run(jnp.arange(17), max_steps=20_000)  # seed 7 rides among others
    single, recs = sim.run_traced(7, max_steps=20_000)
    for name in ("clock", "steps", "events", "violated"):
        b = np.asarray(getattr(batch, name))[7]
        s = np.asarray(getattr(single, name))[0]
        assert np.array_equal(b, s), name
    for leaf_b, leaf_s in zip(
        np.asarray(batch.node.log_cmd)[7], np.asarray(single.node.log_cmd)[0]
    ):
        assert np.array_equal(leaf_b, leaf_s)


@pytest.mark.deep
def test_trace_is_deterministic():
    sim = BatchedSim(make_raft_spec(3), partition_config(horizon_us=1_000_000))
    a = trace_seed(sim, 123, max_steps=4_000)
    b = trace_seed(sim, 123, max_steps=4_000)
    assert a == b
    assert len(a) > 10


@pytest.mark.deep
def test_debug_split_brain_from_trace_alone():
    """run_batch on the buggy spec attaches a device trace for a violating
    seed; the trace alone shows the bug mechanics: a partition splits the
    cluster, then APPENDs are delivered from TWO different leaders in the
    same term window, then the committed-prefix invariant breaks."""
    wl = BatchWorkload(
        spec=split_brain_spec(),
        config=partition_config(loss_rate=0.1),
        max_steps=60_000,
    )
    result = run_batch(range(256), wl, repro_on_host=False, max_traces=1)
    assert result.violations > 0
    assert result.summary["violation_lanes"] == list(
        np.nonzero(result.violated)[0][:32]
    )
    seed, events = next(iter(result.traces.items()))
    assert result.violated[seed]
    text = format_trace(events)
    assert "partition split" in text

    # the trace ends at the violation
    kinds = [e.kind for e in events]
    assert "violation" in kinds
    vio_i = kinds.index("violation")

    # find the last split before the violation, with no heal in between:
    # the partition that exposed the bug
    last_split = max(
        i for i, e in enumerate(events[:vio_i]) if e.kind == "split"
    )
    window = events[last_split:vio_i]
    assert not any(e.kind == "heal" for e in window)

    # split-brain visible in the window, via either catch mechanism, each
    # with its own precise signature:
    # (a) committed-prefix divergence — APPEND traffic from >= 2 distinct
    #     sources (the two concurrent leaders actively diverging), or
    # (b) Leader Completeness firing the moment the other side's candidate
    #     WINS: the last delivery before the violation is the winning
    #     VOTE_RESP, received by a node that is not the appender whose
    #     bogus commits it is missing
    append_srcs = {
        e.src for e in window if e.kind == "deliver" and e.msg_name == "APPEND"
    }
    deliveries = [e for e in window if e.kind == "deliver"]
    two_leaders_appending = len(append_srcs) >= 2
    incomplete_leader_at_election = (
        bool(append_srcs)
        and bool(deliveries)
        and deliveries[-1].msg_name == "VOTE_RESP"
        and deliveries[-1].node not in append_srcs
    )
    assert two_leaders_appending or incomplete_leader_at_election, format_trace(
        window
    )


@pytest.mark.deep
def test_trace_records_crash_restart():
    sim = BatchedSim(
        make_raft_spec(5),
        SimConfig(
            horizon_us=3_000_000,
            crash_interval_lo_us=300_000,
            crash_interval_hi_us=1_000_000,
            restart_delay_lo_us=200_000,
            restart_delay_hi_us=600_000,
        ),
    )
    events = trace_seed(sim, 5, max_steps=20_000, kind_names=("RV", "VR", "AE", "AR", "SN"))
    kinds = [e.kind for e in events]
    assert "crash" in kinds and "restart" in kinds
    # a crash of node k is followed by a restart of the same node
    crash_e = next(e for e in events if e.kind == "crash")
    restart_e = next(e for e in events if e.kind == "restart")
    assert crash_e.node == restart_e.node
    assert restart_e.t_us > crash_e.t_us
