"""Deliberately-invalid speclang spec source for tests/test_speclang.py.

Every construct in `_body` is one the restriction walk
(`speclang.lang.validate_protocol`) exists to refuse at AUTHORING time:
an unbounded `while`, a host callback, a computed prng draw site, and an
ambient-entropy import. The body is never executed — validation parses
this module's source, so the undefined names below are irrelevant.
"""

import random  # noqa: F401  (the ambient-entropy import under test)

from madsim_tpu.speclang.lang import Field, Protocol


def _fields(p):
    return (Field("x"),)


def _body(p, State):
    def on_event(s, nid, src, kind, payload, now, key):
        while nid > 0:  # unbounded control flow
            break
        site = 7
        draw = prng.uniform(key, site)  # noqa: F821  computed draw site
        io_callback(print, None)  # noqa: F821  host re-entry
        return s, None, now + draw

    def first_timer(key, nid):
        return nid

    def restart_timer(s, nid, now, key):
        return now

    def check_invariants(ns, alive, now):
        return True

    return {
        "on_event": on_event,
        "first_timer": first_timer,
        "restart_timer": restart_timer,
        "check_invariants": check_invariants,
    }


PROTOCOL = Protocol(
    name="bad-spec",
    messages=("PING",),
    payload_width=1,
    params=dict(n_nodes=3),
    fields=_fields,
    body=_body,
)
