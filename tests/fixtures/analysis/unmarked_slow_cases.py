# Planted marker-hygiene violations (parsed only; the filename has no
# test_ prefix so pytest never collects these). Expected findings:
# test_soak_unmarked (name) and test_big_sweep_budgeted (runtime note).
import pytest


def test_soak_unmarked():
    pass


def test_quick():
    pass


@pytest.mark.slow
def test_cross_process_marked(tmp_path):
    pass


def test_big_sweep_budgeted():
    """Replays the full acceptance corpus (~45 s warm)."""


@pytest.mark.chaos
def test_chaos_marked_but_budgeted():
    """Chaos tier, but a measured ~60 s budget: chaos does not exclude
    it from the default run, so `slow`/`deep` is still required."""


def test_acceptance_pragmad():  # madsim: allow(marker-hygiene)
    pass
