"""Planted range-rule violations as toy step programs.

Each function is a deliberately broken miniature of the value-safety
pattern the Layer-3 range certifier guards (analysis/ranges.py), with a
clean twin beside it; tests/test_ranges.py traces them with
jax.make_jaxpr and asserts the matching check FIRES (and that the twin
passes). Kept tiny so interval propagation is milliseconds."""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


# --------------------------------------------- narrow counter, no floor

class ToyNode(NamedTuple):
    count: Any  # u16 at rest — the planted wrap


def counter_step(node: ToyNode, tick):
    """A u16 counter incremented EVERY step with no cadence floor: the
    exact bug class `spec.narrow_horizon_us` exists to refuse. With no
    RateFloor declared the certifier must treat the field as step-closed,
    see the +1 escape the dtype, and fire naming the field."""
    wide = node.count.astype(jnp.int32) + 1
    return ToyNode(count=wide.astype(jnp.uint16)), tick


def counter_clamped_step(node: ToyNode, tick):
    """The clean twin: the increment saturates at the dtype boundary, so
    the reachable interval is closed over u16 and certifies floor-free."""
    wide = jnp.minimum(node.count.astype(jnp.int32) + 1, 65535)
    return ToyNode(count=wide.astype(jnp.uint16)), tick


# ------------------------------------- i32 time accumulator that wraps

def time_unit_wrap_step(t_ms, deliver):
    """The classic unit-conversion clock wrap: virtual time kept in
    MILLIseconds fits i32 over the whole horizon, but the microsecond
    conversion (t_ms * 1000) overflows i32 INSIDE the declared horizon.
    Seeded with t_ms in [0, horizon_ms], the multiply's mathematical
    interval escapes int32 and the clock-wrap check must fire."""
    t_us = t_ms * 1000  # wraps past ~2147 virtual seconds
    return t_us + 5_000, deliver + t_us


def time_rebased_step(clock, deliver):
    """The clean twin — the engine's actual discipline: offsets stay
    below the rebase guard (INF_GUARD), so every adder in the step keeps
    the math far inside i32."""
    window = clock + 1_000
    return window, jnp.minimum(deliver, window + 100_000)


def time_scan_wrap_step(t0):
    """A time accumulator wrapped INSIDE a loop: 4000 steps of up to
    1 ms each starting from an in-range offset. The abstract unroll of
    the scan must surface the iteration where the running i32 offset
    escapes int32 (no rebase in sight — the planted bug)."""

    def body(t, _):
        return t + 1_000_000, ()

    t, _ = lax.scan(body, t0, (), length=4000)
    return t


# ----------------------------------------------- dynamic index bounds

def index_oob_step(x, slot):
    """A pool-slot read whose cursor is NOT provably inside the pool:
    slot arrives in [0, 63] but the pool holds 16 slots, and the gather
    promises in-bounds — undefined behavior the certifier must refuse."""
    cursor = jnp.minimum(slot, 63)
    return x.at[cursor].get(mode="promise_in_bounds")


def index_ring_step(x, slot):
    """The clean twin — the engine's ring-cursor idiom: the modulo by
    the static ring depth proves the index in-bounds for any input."""
    cursor = slot % x.shape[0]
    return x.at[cursor].get(mode="promise_in_bounds")
