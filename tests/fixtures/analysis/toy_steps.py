"""Planted jaxpr-rule violations as toy step programs.

Each function is a deliberately broken miniature of the engine pattern a
Layer-1 rule guards; tests/test_analysis.py traces them with
jax.make_jaxpr and asserts the matching rule FIRES (and that its clean
twin passes). Kept tiny so tracing is milliseconds."""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from madsim_tpu.tpu import prng


# ------------------------------------------------------------- callbacks

def clean_step(x):
    return x * 2


def callback_step(x):
    jax.debug.print("x = {}", x)  # host sync inside the step
    return x * 2


# ------------------------------------------------------------- rng taint

def pure_schedule_draw(key0, k):
    # victim draw indexed by the occurrence counter: the legal pattern
    return prng.randint(key0, 203, 0, 5, index=k)


def impure_schedule_draw(key0, clock):
    # drawing the victim at an index derived from the lane CLOCK couples
    # the fault schedule to the trajectory — the exact bug class
    return prng.randint(key0, 203, 0, 5, index=clock)


def impure_draw_inside_jit(key0, clock):
    # the same bug hidden behind an inline-jitted helper: the mix eqns
    # live in a pjit sub-jaxpr, and the witness must still name the
    # clock leaf via the enclosing top-level equation
    return jax.jit(
        lambda k, c: prng.randint(k, 203, 0, 5, index=c)
    )(key0, clock)


def clean_funnel(key, payload):
    new_key = prng.fold(key, 1)
    coin = prng.uniform(prng.fold(key, 7), 33)
    return new_key, coin + payload[..., 0]


def contaminated_funnel(key, payload):
    # folding protocol state INTO the carried key poisons every
    # downstream step's draws
    new_key = prng.fold(key, payload[..., 0])
    return new_key, jnp.zeros_like(payload[..., 0])


# ------------------------------------------------------------ leaky refill

def clean_refill(key, key0, done, qseeds, cursor):
    # the legal continuous-batching refill: a retiring lane's NEW chain
    # roots derive from its admitted queue seed alone (key_from(seed)),
    # exactly what a fresh chunked lane's _init would draw — survivors
    # keep their chains untouched (the select's bool mask carries no
    # value taint)
    ji = done.astype(jnp.int32)
    adm = jnp.clip(cursor + jnp.cumsum(ji) - ji, 0, qseeds.shape[0] - 1)
    fresh = prng.key_from(jnp.take(qseeds, adm, axis=0))
    new_key = jnp.where(done, fresh, prng.fold(key, 1))
    new_key0 = jnp.where(done, fresh, key0)
    victim = prng.randint(new_key0, 203, 0, 5)  # schedule draw: key0 only
    return new_key, new_key0, victim


def leaky_refill(key, key0, done, qseeds, cursor):
    # the planted refill leak: the refilled lane's init FOLDS A
    # SURVIVOR'S RUNNING KEY CHAIN into its new schedule root — its
    # fault schedule is then a function of how far other work happened
    # to have run, not of (seed, clause, occurrence); rng-taint must
    # catch the key0-rooted draw mixing chain material
    ji = done.astype(jnp.int32)
    adm = jnp.clip(cursor + jnp.cumsum(ji) - ji, 0, qseeds.shape[0] - 1)
    fresh = prng.key_from(jnp.take(qseeds, adm, axis=0))
    contaminated = prng.fold(fresh, jnp.roll(key, 1))  # survivor's chain
    new_key = jnp.where(done, contaminated, prng.fold(key, 1))
    new_key0 = jnp.where(done, contaminated, key0)
    victim = prng.randint(new_key0, 203, 0, 5)  # a schedule draw off it
    return new_key, new_key0, victim


# ------------------------------------------------ leaky device-loop ring

def clean_devloop_ring(key, meta_key, counter, ring_seed, ring_n, done):
    # the legal device-loop generation boundary (r19): a mutant's new
    # schedule root derives from a corpus-ring PARENT seed alone, picked
    # by a MetaRng draw — (meta_key, counter) is the host MetaRng's
    # murmur cursor, deliberately disjoint from every lane's schedule
    # key, and survivors' running chains never enter the ring
    d0 = prng.bits(meta_key, 301, index=counter)
    pidx = jnp.clip(
        (d0 % jnp.maximum(ring_n, 1).astype(jnp.uint32)).astype(jnp.int32),
        0, ring_seed.shape[0] - 1,
    )
    root = prng.key_from(ring_seed[pidx])
    new_key = jnp.where(done, root, prng.fold(key, 1))
    victim = prng.randint(root, 203, 0, 5)  # schedule draw: ring seed only
    return new_key, victim


def leaky_ring(key, meta_key, counter, ring_seed, ring_n, done):
    # the planted device-loop leak: the corpus-ring scatter FOLDS A
    # SURVIVOR LANE'S RUNNING KEY CHAIN into the stored seed — every
    # mutant descended from that row then runs a fault schedule that is
    # a function of how far other lanes happened to have run, not of
    # (seed, clause, occurrence); rng-taint must catch the ring-rooted
    # draw mixing chain (KEY2) material
    leaked = ring_seed.at[0].set(prng.fold(ring_seed[0], key[0]))
    d0 = prng.bits(meta_key, 301, index=counter)
    pidx = jnp.clip(
        (d0 % jnp.maximum(ring_n, 1).astype(jnp.uint32)).astype(jnp.int32),
        0, ring_seed.shape[0] - 1,
    )
    root = prng.key_from(leaked[pidx])
    new_key = jnp.where(done, root, prng.fold(key, 1))
    victim = prng.randint(root, 203, 0, 5)  # a schedule draw off it
    return new_key, victim


# ------------------------------------------------- sharded collectives

def clean_sharded_segment(mesh):
    """The legal multi-chip refill shape: each device steps its own
    block, no cross-device primitive anywhere (engine._sharded_segment's
    contract, docs/multichip.md)."""
    from jax.experimental.shard_map import shard_map

    P = jax.sharding.PartitionSpec

    def seg(x):
        return x * 2 + 1

    return shard_map(
        seg, mesh=mesh, in_specs=(P(mesh.axis_names[0]),),
        out_specs=P(mesh.axis_names[0]), check_rep=False,
    )


def leaky_sharded_segment(mesh):
    """The planted multi-chip leak: a psum inside the sharded segment —
    every device's step now depends on every other device's state, so
    per-device rows stop being the pure per-seed function the mesh
    bit-identity contract requires. The lane-independence rule's
    collective walk must flag it by exact primitive name."""
    from jax.experimental.shard_map import shard_map

    P = jax.sharding.PartitionSpec
    axis = mesh.axis_names[0]

    def seg(x):
        return x + jax.lax.psum(x.sum(), axis)

    return shard_map(
        seg, mesh=mesh, in_specs=(P(axis),), out_specs=P(axis),
        check_rep=False,
    )


# ----------------------------------------------------------------- dtype

def time_f32_step(timer):
    # the r1 clock-skew bug: f32 multiply on a time value loses integer
    # microseconds past 2^24 us
    return (timer.astype(jnp.float32) * jnp.float32(1.00005)).astype(
        jnp.int32
    )


def time_int_step(timer):
    from madsim_tpu.tpu.engine import scale_delay_ppm

    return scale_delay_ppm(timer, 50)


# ------------------------------------------------------ lane independence

def lane_coupled_step(x):
    # subtracting a cross-lane mean entangles every lane with the batch
    return x - x.mean(axis=0, keepdims=True)


def lane_coupled_rhs_matmul(m, x):
    # x: [L, F]; contracting the LANE axis on the RHS operand
    return m @ x


def lane_coupled_transposed(x):
    # the lane axis moved to position 1 by the transpose, then contracted
    return x.T @ x


def lane_local_step(x):
    return x - x.mean(axis=1, keepdims=True)


# -------------------------------------------------------------- donation

class ToyHot(NamedTuple):
    key: Any
    x: Any


class ToyCold(NamedTuple):
    acc: Any


class ToyConst(NamedTuple):
    key0: Any
    scale: Any


HOT_NAMES = ("hot.key", "hot.x")
COLD_NAMES = ("cold.acc",)
CONST_NAMES = ("const.key0", "const.scale")


def toy_state(lanes: int = 13):
    hot = ToyHot(
        key=jax.ShapeDtypeStruct((lanes,), jnp.uint32),
        x=jax.ShapeDtypeStruct((lanes,), jnp.int32),
    )
    cold = ToyCold(acc=jax.ShapeDtypeStruct((lanes,), jnp.int32))
    const = ToyConst(
        key0=jax.ShapeDtypeStruct((lanes,), jnp.uint32),
        scale=jax.ShapeDtypeStruct((), jnp.int32),
    )
    return hot, cold, const


def good_toy_step(hot, cold, const):
    coin = (prng.bits(const.key0, 5) & 1).astype(jnp.int32)
    x2 = hot.x + const.scale + coin
    return ToyHot(prng.fold(hot.key, 1), x2), ToyCold(cold.acc + x2)


def widened_toy_step(hot, cold, const):
    # hot.x leaves the step as f32: no output matches its buffer, so the
    # leaf cannot be donated — the donation-coverage regression
    x2 = (hot.x + const.scale).astype(jnp.float32)
    return ToyHot(prng.fold(hot.key, 1), x2), ToyCold(cold.acc)


def good_toy_run(hot, cold, const, n=4):
    def body(carry):
        h, c, i = carry
        h2, c2 = good_toy_step(h, c, const)
        return h2, c2, i + 1

    def cond(carry):
        return carry[2] < n

    h, c, _ = jax.lax.while_loop(cond, body, (hot, cold, jnp.int32(0)))
    return h, c


def leaky_toy_run(hot, cold, const, n=4):
    # const.scale rides the while carry: donation rotates a loop
    # invariant through fresh buffers every segment — the regression the
    # hot/cold/const split can silently lose
    def body(carry):
        h, c, s, i = carry
        h2, c2 = good_toy_step(h, c, ToyConst(const.key0, s))
        return h2, c2, s, i + 1

    def cond(carry):
        return carry[3] < n

    h, c, _, _ = jax.lax.while_loop(
        cond, body, (hot, cold, const.scale, jnp.int32(0))
    )
    return h, c
