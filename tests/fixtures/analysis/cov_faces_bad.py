# Planted both-faces violation: the device coverage chain folds FIVE
# fields while the trace mirror (and the COV_FIELDS registry) carry four
# — the exact silent mirror break the rule exists for. Parsed only,
# never imported (prng/fold32/COV_SALT are unresolved on purpose).

COV_FIELDS = ("node", "src", "kind", "bucket")


def _step_traced(state):
    ck = prng.fold(jnp.uint32(COV_SALT), node_ids)
    ck = prng.fold(ck, src_w)
    ck = prng.fold(ck, kind_w)
    ck = prng.fold(ck, bucket)
    ck = prng.fold(ck, payload_crc)  # the unmirrored fifth field
    idx = prng.mix(ck) % jnp.uint32(COV_BITS)
    return idx


def cov_index(node, src=-1, kind=-1, bucket=0):
    ck = fold32(COV_SALT, node)
    ck = fold32(ck, src)
    ck = fold32(ck, kind)
    ck = fold32(ck, bucket)
    return mix32(ck) % COV_BITS


def bitmap_from_trace(records, lane=0):
    # both event faces read, so only the chain mismatch fires
    if records.msg_fired[lane] or records.timer_fired[lane]:
        return cov_index(0)
    return 0
