# Planted both-faces SUBSTITUTION violation: both faces fold FOUR fields
# (counts agree!) but the device face swapped `bucket` for `payload_crc`
# — only the field-name sequence comparison against COV_FIELDS catches
# it. Parsed only, never imported.

COV_FIELDS = ("node", "src", "kind", "bucket")


def _step_traced(state):
    ck = prng.fold(jnp.uint32(COV_SALT), node_ids)
    ck = prng.fold(ck, src_w)
    ck = prng.fold(ck, kind_w)
    ck = prng.fold(ck, payload_crc)  # substituted: registry says bucket
    return prng.mix(ck) % jnp.uint32(COV_BITS)


def cov_index(node, src=-1, kind=-1, bucket=0):
    ck = fold32(COV_SALT, node)
    ck = fold32(ck, src)
    ck = fold32(ck, kind)
    ck = fold32(ck, bucket)
    return mix32(ck) % COV_BITS


def bitmap_from_trace(records, lane=0):
    if records.msg_fired[lane] or records.timer_fired[lane]:
        return cov_index(0)
    return 0
