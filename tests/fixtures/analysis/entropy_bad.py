# Planted ambient-entropy violations for the analysis linter
# (tests/test_analysis.py). This file is PARSED, never imported or
# collected (no test_ filename prefix). Expected findings: exactly seven
# — time.time, random.random, np.random.rand, os.urandom, npr.rand,
# default_rng, date.today — with the pragma'd urandom and the
# measurement clock allowed.
import os
import random
import time
import numpy.random as npr
from datetime import date
from numpy.random import default_rng

import numpy as np


def leaks_ambient_entropy():
    t = time.time()  # violation: wall clock
    r = random.random()  # violation: unseeded stdlib RNG
    n = np.random.rand(3)  # violation: ambient numpy RNG
    b = os.urandom(8)  # violation: OS entropy
    n2 = npr.rand(2)  # violation: aliased numpy.random module
    g = default_rng()  # violation: from-imported numpy.random name
    d = date.today()  # violation: wall-clock date
    allowed = os.urandom(4)  # madsim: allow(ambient-entropy)
    ok = time.perf_counter()  # allowed: measurement clock, not behavior
    return t, r, n, b, n2, g, d, allowed, ok
