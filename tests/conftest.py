import os

# TPU/sharding tests run on a virtual 8-device CPU mesh. Must be configured
# before any jax import; the environment may pre-set JAX_PLATFORMS to a real
# accelerator (e.g. "axon"), so override rather than setdefault.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass
