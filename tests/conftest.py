import os
import sys

# the package is used from a checkout, not an install: make the suite
# runnable from any cwd by putting the repo root on sys.path
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# TPU/sharding tests run on a virtual 8-device CPU mesh. Must be configured
# before any jax import; the environment may pre-set JAX_PLATFORMS to a real
# accelerator (e.g. "axon"), so override rather than setdefault.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Persistent XLA compilation cache: the TPU-engine tests compile dozens of
# large programs (~18s each cold); caching cuts repeat suite runs by
# several minutes. /tmp is machine-local, so a container migration can't
# replay AOT code compiled for a different CPU. The cache loader logs
# spurious ERROR lines about "prefer-no-scatter" pseudo-features differing
# from the detected host (a cosmetic XLA:CPU logging bug on same-machine
# reloads), so silence XLA's C++ log stream for test runs — test failures
# surface as Python exceptions, never via that stream.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

try:
    import jax

    jax.config.update("jax_platforms", "cpu")
    if not os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        # per-uid (like the uds socket dir): a shared path would leave
        # second users unable to write AND trusting artifacts they don't own
        jax.config.update(
            "jax_compilation_cache_dir", f"/tmp/madsim_tpu_jaxcache-{os.getuid()}"
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
except ImportError:
    pass
