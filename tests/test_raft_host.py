"""Host-runtime Raft workload tests (the MadRaft-analog integration suite)."""

import pytest

from madsim_tpu.workloads.raft_host import InvariantViolation, fuzz_one_seed


def test_raft_host_commits_under_chaos():
    r = fuzz_one_seed(1, virtual_secs=10.0)
    assert max(r["commits"]) >= 0
    assert r["events"] > 100


def test_raft_host_deterministic():
    assert fuzz_one_seed(3, virtual_secs=5.0) == fuzz_one_seed(3, virtual_secs=5.0)


def test_raft_host_quiet_network_full_commit():
    r = fuzz_one_seed(7, virtual_secs=10.0, loss_rate=0.0, chaos=False)
    assert r["commits"] == [23, 23, 23, 23, 23]


def test_raft_host_buggy_version_caught():
    # seed 5 trips the eager-commit bug (found by sweeping seeds 0..16)
    with pytest.raises(InvariantViolation):
        for seed in (5, 8, 11, 12, 14):
            fuzz_one_seed(seed, virtual_secs=10.0, buggy=True, loss_rate=0.3)
