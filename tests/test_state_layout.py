"""The r8 state-layout lint + golden trajectory digests (ISSUE 6).

Three guarantees, each of which a future PR can silently break:

1. **The layout table.** Every SimState leaf's dtype is DECLARED here; a
   leaf that widens (u8 -> i32, packed u32 plane -> bool) fails the test
   with the offending field named. This is the lint that keeps the carry
   from re-inflating — the whole r8 seeds/s win is these bytes
   (docs/state_layout.md).

2. **Value preservation.** Bit-packing and dtype narrowing are storage
   transforms only: packed planes round-trip exactly, and a spec run
   with its `narrow_fields` stripped produces bit-identical trajectories.

3. **Golden digests.** A canonical (layout-independent: everything
   widened to i64, planes unpacked) digest of a 1500-step chaotic
   trajectory is pinned for all five workloads. The SAME constants were
   produced by the pre-compaction r7 engine — layout-version r8 changed
   the bytes at rest, not one trajectory. Narrowing that legitimately
   changes a digest must re-bless these constants with a layout-version
   note here and in docs/state_layout.md. (The ONE intentional behavior
   change of r8 — f32 clock-skew math -> exact integer ppm — is excluded
   by construction: the digest plan carries no ClockSkew clause. Its
   regression coverage lives in test_nemesis.py::test_skew_*.)
"""

import dataclasses
import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu import nemesis
from madsim_tpu.tpu import nemesis as tpu_nemesis
from madsim_tpu.tpu import bitpack
from madsim_tpu.tpu.chain import make_chain_spec
from madsim_tpu.tpu.engine import (
    BatchedSim,
    COLD_FIELDS,
    ConstState,
    merge_state,
    split_state,
    summarize,
)
from madsim_tpu.tpu.kv import make_kv_spec
from madsim_tpu.tpu.paxos import make_paxos_spec
from madsim_tpu.tpu.raft import make_raft_spec
from madsim_tpu.tpu.spec import SimConfig
from madsim_tpu.tpu.twopc import make_twopc_spec

SPECS = {
    "raft": make_raft_spec,
    "paxos": make_paxos_spec,
    "kv": make_kv_spec,
    "twopc": make_twopc_spec,
    "chain": make_chain_spec,
}

# ---------------------------------------------------------------- the table
#
# Declared dtype for every SimState leaf of the reference raft sim
# (default spec, default config, L=4 lanes, N=5 nodes). Shapes are given
# as (L, N)-relative so the table survives config tweaks; dtypes are
# EXACT. A new engine field must be added here deliberately — the test
# fails on any leaf the table does not cover.
L, N = 4, 5
LAYOUT = {
    "clock": ("int32", (L,)),
    "epoch": ("int32", (L,)),
    "key": ("uint32", (L,)),
    "key0": ("uint32", (L,)),
    "done": ("bool", (L,)),
    "violated": ("bool", (L,)),
    "violation_at": ("int32", (L,)),
    "violation_epoch": ("int32", (L,)),
    "violation_step": ("int32", (L,)),
    "deadlocked": ("bool", (L,)),
    "steps": ("int32", (L,)),
    "events": ("int32", (L,)),
    "overflow": ("int32", (L,)),
    "dead_drops": ("int32", (L,)),
    # membership churn (r17): packed membership plane + epoch + drop
    # counter are always present (the reconfig clause toggles behavior,
    # not layout)
    "member_p": ("uint32", (L, 1)),
    "member_epoch": ("int32", (L,)),
    "nonmember_drops": ("int32", (L,)),
    # durability chaos (r18): the lost-unsynced-state counter is always
    # present; the watermark itself (`dur`) exists only when a DiskFault
    # clause meets a spec with durable_fields — disk-off sweeps pay ZERO
    # watermark bytes
    "unsynced_loss": ("int32", (L,)),
    "dur": None,
    "fires": ("int32", (L, 16)),
    "occ_fired": None,
    # bit-packed planes (bitpack.py): bool would cost 8x in the carry
    "alive_p": ("uint32", (L, 1)),
    "crashed": ("int32", (L,)),
    "chaos_at": ("int32", (L,)),
    "link_ok_p": ("uint32", (L, N, 1)),
    "partitioned": ("bool", (L,)),
    "part_at": ("int32", (L,)),
    "timer": ("int32", (L, N)),
    # raft node pytree — narrow per spec.narrow_fields (raft.py)
    "node.term": ("uint16", (L, N)),
    "node.voted_for": ("int8", (L, N)),
    "node.role": ("uint8", (L, N)),
    "node.votes": ("uint8", (L, N)),
    "node.base": ("int32", (L, N)),
    "node.head": ("int32", (L, N)),
    "node.base_hash": ("int32", (L, N)),
    "node.base_term": ("uint16", (L, N)),
    "node.log_term": ("uint16", (L, N, 24)),
    "node.log_cmd": ("int32", (L, N, 24)),
    "node.log_chain": ("uint32", (L, N, 24)),
    "node.log_len": ("int32", (L, N)),
    "node.commit": ("int32", (L, N)),
    "node.next_idx": ("int32", (L, N, N)),
    "node.match_idx": ("int32", (L, N, N)),
    "node.next_cmd": ("int32", (L, N)),
    "node.reply_parity": ("uint8", (L, N)),
    # message pool: packed validity, u8 kinds, i32 times/payload
    "msgs.valid_p": ("uint32", (L, N, 2)),
    "msgs.deliver": ("int32", (L, 50)),
    "msgs.kind": ("uint8", (L, 50)),
    "msgs.payload": ("int32", (L, 50, 6)),
    # causal lineage (r12, docs/causality.md): None outside lineage mode
    # — lineage-off sweeps pay ZERO bytes (structure untouched; the
    # lineage-mode dtypes are pinned in LINEAGE_LAYOUT below)
    "msgs.sent_eid": None,
    "lin": None,
    "strag": None,
    "nem": None,
    "ctl": None,
    "cov": None,
    # continuous batching (r9, docs/continuous_batching.md): both None
    # outside refill mode — plain sweeps carry zero refill bytes
    "queue": None,
    "refill": None,
    # device-resident search (r19, docs/explore.md): None outside
    # device-loop mode — plain and refill sweeps carry zero DevLoop bytes
    "loop": None,
}

# the refill-mode additions (BatchedSim.init_refill with A admissions
# over L lanes): the admission queue is loop-INVARIANT (const side,
# never donated/rewritten), the RefillLog is cold carry — per-admission
# result rows plus the cursor/occupancy scalars. Dtypes are EXACT here
# for the same reason as LAYOUT: silent widening re-inflates the carry.
A = 9
REFILL_LAYOUT = {
    "queue.seeds": ("uint32", (A,)),
    "queue.off": None,  # triage-mode only (plain sweep queues seeds)
    "queue.occ": None,
    "queue.rate_scale": None,
    "queue.h_epoch": None,
    "queue.h_off": None,
    "refill.cursor": ("int32", ()),
    "refill.admitted": ("int32", (L,)),
    "refill.step_cap": ("int32", ()),
    "refill.iters": ("int32", ()),
    "refill.busy": ("int32", (L,)),
    "refill.retired": ("int32", (A,)),
    "refill.violated": ("bool", (A,)),
    "refill.deadlocked": ("bool", (A,)),
    "refill.violation_at": ("int32", (A,)),
    "refill.violation_epoch": ("int32", (A,)),
    "refill.violation_step": ("int32", (A,)),
    "refill.steps": ("int32", (A,)),
    "refill.events": ("int32", (A,)),
    "refill.overflow": ("int32", (A,)),
    "refill.dead_drops": ("int32", (A,)),
    "refill.nonmember_drops": ("int32", (A,)),
    "refill.unsynced_loss": ("int32", (A,)),
    "refill.clock": ("int32", (A,)),
    "refill.epoch": ("int32", (A,)),
    "refill.fires": ("int32", (A, 16)),
    "refill.occ_fired": None,  # nemesis schedule clauses only
    "refill.cov_bitmap": None,  # coverage mode only
    "refill.cov_hiwater": None,
    "refill.cov_transitions": None,
}

# the causal-lineage additions (r12, BatchedSim(lineage=True);
# docs/causality.md): per-node Lamport clocks + the global per-lane
# event counter in the hot carry, and a NARROW u16 send-event stamp per
# pool slot — the stamp is the plane's dominant cost, and u16 (rolling-
# window reconstruction against the eid counter) is what keeps the
# whole plane under the 15% carry budget bench_smoke asserts. Silent
# widening of any of these re-inflates the carry and fails here by name.
LINEAGE_LAYOUT = {
    "lin.lam": ("int32", (L, N)),
    "lin.eid": ("uint32", (L,)),
    "msgs.sent_eid": ("uint16", (L, 50)),
}


def _walk(prefix, obj, out):
    if obj is None or not hasattr(obj, "_fields"):
        out[prefix] = obj
        return
    for f in obj._fields:
        _walk(f if not prefix else f"{prefix}.{f}", getattr(obj, f), out)


def test_simstate_layout_table():
    """Every leaf matches its declared dtype/shape; no undeclared leaves.

    THE layout lint: silently widening any leaf (or un-packing a plane)
    re-inflates the sweep carry and fails here by name.
    """
    sim = BatchedSim(make_raft_spec())
    st = sim.init(jnp.arange(L, dtype=jnp.uint32))
    leaves: dict = {}
    _walk("", st, leaves)
    undeclared = set(leaves) - set(LAYOUT)
    assert not undeclared, (
        f"SimState grew undeclared leaves {sorted(undeclared)} — declare "
        "their dtype in LAYOUT (and justify it in docs/state_layout.md)"
    )
    missing = set(LAYOUT) - set(leaves)
    assert not missing, f"declared leaves vanished: {sorted(missing)}"
    for name, want in LAYOUT.items():
        got = leaves[name]
        if want is None:
            assert got is None, f"{name}: expected None, got {got!r}"
            continue
        dt, shape = want
        assert str(got.dtype) == dt, (
            f"layout regression: {name} is {got.dtype}, declared {dt} — "
            "if intentional, update LAYOUT + docs/state_layout.md"
        )
        assert tuple(got.shape) == shape, (
            f"{name}: shape {tuple(got.shape)} != declared {shape}"
        )


def test_refill_state_layout_table():
    """The refill-mode leaves match their declared dtypes/shapes too, and
    the refill carry PARTITION holds: the queue is const (loop-invariant,
    never in the donated carry), key0/ctl ride the carry (a refilled lane
    rewrites them), and RefillLog is cold."""
    from madsim_tpu.tpu.engine import carry_partition

    sim = BatchedSim(make_raft_spec())
    st = sim.init_refill(jnp.arange(A, dtype=jnp.uint32), lanes=L)
    leaves: dict = {}
    _walk("", st, leaves)
    declared = dict(LAYOUT)
    declared.update(REFILL_LAYOUT)
    declared.pop("queue")
    declared.pop("refill")
    undeclared = set(leaves) - set(declared)
    assert not undeclared, (
        f"refill state grew undeclared leaves {sorted(undeclared)} — "
        "declare them in REFILL_LAYOUT"
    )
    for name, want in declared.items():
        got = leaves[name]
        if want is None:
            assert got is None, f"{name}: expected None, got {got!r}"
            continue
        dt, shape = want
        assert str(got.dtype) == dt, f"{name}: {got.dtype} != {dt}"
        assert tuple(got.shape) == shape, (
            f"{name}: shape {tuple(got.shape)} != declared {shape}"
        )
    part = carry_partition(st)
    assert all(n.startswith("queue.") for n in part["const"]), part["const"]
    assert "key0" in part["hot"], "refilled lanes must rewrite key0"
    assert any(n.startswith("refill.") for n in part["cold"])
    assert not any(n.startswith("queue.") for n in part["hot"] + part["cold"])


def test_lineage_state_layout_table():
    """Lineage-mode leaves match their declared narrow dtypes, ride the
    HOT carry (Lamport clocks rewrite every step; a refilled lane adopts
    fresh ones), and lineage-OFF states carry exactly zero lineage bytes
    (the `lin`/`msgs.sent_eid` None rows of LAYOUT pin that half)."""
    from madsim_tpu.tpu.engine import carry_partition

    sim = BatchedSim(make_raft_spec(), lineage=True)
    st = sim.init(jnp.arange(L, dtype=jnp.uint32))
    leaves: dict = {}
    _walk("", st, leaves)
    declared = dict(LAYOUT)
    declared.update(LINEAGE_LAYOUT)
    undeclared = set(leaves) - set(declared)
    assert not undeclared, (
        f"lineage state grew undeclared leaves {sorted(undeclared)} — "
        "declare them in LINEAGE_LAYOUT"
    )
    for name, want in LINEAGE_LAYOUT.items():
        got = leaves[name]
        dt, shape = want
        assert str(got.dtype) == dt, (
            f"lineage layout regression: {name} is {got.dtype}, declared "
            f"{dt} — the u16 stamp is what keeps the plane inside the "
            "15% carry budget (docs/causality.md)"
        )
        assert tuple(got.shape) == shape, (
            f"{name}: shape {tuple(got.shape)} != declared {shape}"
        )
    part = carry_partition(st)
    for name in ("lin.lam", "lin.eid", "msgs.sent_eid"):
        assert name in part["hot"], f"{name} must ride the hot carry"


def _golden_lineage_one(name):
    """The lineage plane is OBSERVE-ONLY: the canonical golden digests
    (pinned pre-lineage) are unchanged with lineage=True — same bar
    coverage=True met in r7."""
    cfg = tpu_nemesis.compile_plan(
        CHAOS_PLAN, SimConfig(horizon_us=30_000_000)
    )
    sim = BatchedSim(SPECS[name](), cfg, lineage=True)
    st = sim.run(jnp.arange(16, dtype=jnp.uint32), max_steps=1500,
                 dispatch_steps=1500)
    assert canonical_digest(st) == GOLDEN[name], (
        f"{name}: lineage=True changed the golden trajectory digest — "
        "the lineage plane fed a draw or a handler (docs/causality.md)"
    )
    assert summarize(st)["total_events"] > 0


@pytest.mark.chaos
def test_golden_digest_raft_with_lineage():
    _golden_lineage_one("raft")


@pytest.mark.deep
@pytest.mark.chaos
@pytest.mark.parametrize("name", ["paxos", "kv", "twopc", "chain"])
def test_golden_digest_rest_with_lineage(name):
    _golden_lineage_one(name)


def test_cold_const_split_partition():
    """split_state/merge_state is a lossless partition of SimState: every
    leaf lands in exactly one of hot/cold/const, and merge inverts it."""
    sim = BatchedSim(make_raft_spec())
    st = sim.init(jnp.arange(L, dtype=jnp.uint32))
    hot, cold, const = split_state(st)
    # hot nulls out everything cold/const carries
    for f in COLD_FIELDS:
        assert getattr(hot, f) is None, f"{f} leaked into the hot carry"
    for f in ConstState._fields:
        if f == "skew_ppm":
            continue  # lives under nem, None without a skew clause
        assert getattr(hot, f) is None, f"{f} leaked into the hot carry"
    back = merge_state(hot, cold, const)
    la, lb = jax.tree_util.tree_leaves(st), jax.tree_util.tree_leaves(back)
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------------- bit packing


@pytest.mark.parametrize("k", [1, 5, 31, 32, 33, 50, 64, 100])
def test_pack_roundtrip(k):
    rng = np.random.default_rng(k)
    m = jnp.asarray(rng.random((7, 3, k)) < 0.5)
    packed = bitpack.pack_bits(m)
    assert packed.dtype == jnp.uint32
    assert packed.shape == (7, 3, bitpack.packed_words(k))
    out = bitpack.unpack_bits(packed, k)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(m))
    # trailing pad bits stay zero (packed words compare equal iff planes do)
    repacked = bitpack.pack_bits(out)
    np.testing.assert_array_equal(np.asarray(repacked), np.asarray(packed))


def test_full_mask_word():
    for n in range(33):
        w = bitpack.full_mask_word(n)
        got = bitpack.unpack_bits(jnp.asarray([w], jnp.uint32), 32)
        np.testing.assert_array_equal(
            np.asarray(got), np.arange(32) < n
        )
    with pytest.raises(ValueError):
        bitpack.full_mask_word(33)


# ------------------------------------------------- narrowing invariance

CHAOS_PLAN = nemesis.FaultPlan(
    name="layout",
    clauses=(
        nemesis.Crash(interval_lo_us=300_000, interval_hi_us=900_000,
                      down_lo_us=200_000, down_hi_us=600_000),
        nemesis.Partition(interval_lo_us=400_000, interval_hi_us=1_200_000,
                          heal_lo_us=300_000, heal_hi_us=900_000),
        nemesis.MsgLoss(rate=0.05),
    ),
)


def _run_pair(spec, lanes=16, steps=1200):
    """Run (narrow, wide-stripped) twins and return both final states."""
    assert spec.narrow_fields, f"{spec.name}: narrow table missing"
    cfg = tpu_nemesis.compile_plan(CHAOS_PLAN, SimConfig(horizon_us=30_000_000))
    wide = dataclasses.replace(spec, narrow_fields=None)
    seeds = jnp.arange(lanes, dtype=jnp.uint32)
    simN, simW = BatchedSim(spec, cfg), BatchedSim(wide, cfg)
    stN = simN.run(seeds, max_steps=steps, dispatch_steps=steps)
    stW = simW.run(seeds, max_steps=steps, dispatch_steps=steps)
    return simN, stN, stW


def _assert_states_match(simN, stN, stW):
    nodeN = simN._widen_node(stN.node)
    for f, a, b in zip(
        type(nodeN)._fields,
        jax.tree_util.tree_leaves(nodeN),
        jax.tree_util.tree_leaves(stW.node),
    ):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"node.{f} diverged"
        )
    for f in ("clock", "steps", "events", "violated", "done", "timer",
              "crashed", "fires"):
        np.testing.assert_array_equal(
            np.asarray(getattr(stN, f)), np.asarray(getattr(stW, f)),
            err_msg=f"{f} diverged",
        )
    np.testing.assert_array_equal(
        np.asarray(stN.alive), np.asarray(stW.alive), err_msg="alive"
    )
    np.testing.assert_array_equal(
        np.asarray(stN.msgs.valid), np.asarray(stW.msgs.valid),
        err_msg="msgs.valid",
    )


@pytest.mark.chaos
def test_narrowing_invariance_raft():
    _assert_states_match(*_run_pair(make_raft_spec()))


@pytest.mark.chaos
def test_narrowing_invariance_twopc():
    _assert_states_match(*_run_pair(make_twopc_spec()))


@pytest.mark.deep
@pytest.mark.chaos
@pytest.mark.parametrize("name", ["paxos", "kv", "chain"])
def test_narrowing_invariance_rest(name):
    _assert_states_match(*_run_pair(SPECS[name]()))


def test_narrow_fields_validation():
    """The engine rejects bad narrow tables loudly at construction."""
    spec = make_raft_spec()
    with pytest.raises(ValueError, match="time_fields"):
        BatchedSim(dataclasses.replace(
            spec,
            time_fields=("term",),
            narrow_fields={"term": jnp.uint16},
        ))
    with pytest.raises(ValueError, match="unknown node-state field"):
        BatchedSim(dataclasses.replace(
            spec, narrow_fields={"nonesuch": jnp.uint8}
        )).init(jnp.arange(2, dtype=jnp.uint32))
    with pytest.raises(ValueError, match="not narrower"):
        BatchedSim(dataclasses.replace(
            spec, narrow_fields={"commit": jnp.float32}
        )).init(jnp.arange(2, dtype=jnp.uint32))


def test_narrow_horizon_cap_enforced():
    """Rate-argument narrow bounds (twopc's 1-tid-per-timer-floor i16,
    raft's N-per-election_lo u16) only hold up to the spec-declared
    horizon; a longer soak must be REFUSED, not allowed to wrap counters
    silently — and clock skew (which shrinks timer floors) derates it."""
    tp = make_twopc_spec()
    assert tp.narrow_horizon_us is not None
    # at the cap: fine
    BatchedSim(tp, SimConfig(horizon_us=tp.narrow_horizon_us))
    with pytest.raises(ValueError, match="safe horizon"):
        BatchedSim(tp, SimConfig(horizon_us=tp.narrow_horizon_us + 1))
    # stripping the table re-admits the long soak (wide i32 counters)
    BatchedSim(
        dataclasses.replace(tp, narrow_fields=None),
        SimConfig(horizon_us=tp.narrow_horizon_us + 1),
    )
    rf = make_raft_spec()
    with pytest.raises(ValueError, match="safe horizon"):
        BatchedSim(rf, SimConfig(horizon_us=rf.narrow_horizon_us + 1))
    # a 20% skew shrinks timer floors by up to 20% — a horizon at the
    # unskewed cap must now be refused (derated cap), and one inside the
    # derated cap accepted
    skewed = SimConfig(
        horizon_us=tp.narrow_horizon_us, nem_skew_max_ppm=200_000
    )
    with pytest.raises(ValueError, match="skew-derating|safe horizon"):
        BatchedSim(tp, skewed)
    BatchedSim(tp, dataclasses.replace(
        skewed, horizon_us=tp.narrow_horizon_us * 8 // 10
    ))


def test_kind_dtype_follows_declared_vocabulary():
    """Pool `kind` narrows to u8 only when the spec declares its kind
    vocabulary (msg_kind_names, dense <= 256); undeclared specs might use
    sparse values >= 256, which a blind u8 cast would silently wrap."""
    named = BatchedSim(make_raft_spec())
    st = named.init(jnp.arange(2, dtype=jnp.uint32))
    assert st.msgs.kind.dtype == jnp.uint8
    anon = BatchedSim(
        dataclasses.replace(make_raft_spec(), msg_kind_names=None)
    )
    st2 = anon.init(jnp.arange(2, dtype=jnp.uint32))
    assert st2.msgs.kind.dtype == jnp.int32


def test_sum64_lane_bound_enforced():
    """_sum64's u32 partials only stay exact for <= 65536 lanes; a bigger
    axis must raise, not wrap."""
    from madsim_tpu.tpu.engine import _sum64

    _sum64(jnp.zeros((8,), jnp.int32))
    with pytest.raises(ValueError, match="65536"):
        _sum64(jnp.zeros((65537,), jnp.int32))


# --------------------------------------------------------- golden digests
#
# Pinned canonical digests of a 1500-step, 16-lane chaotic trajectory.
# Layout-version r8: these constants were produced IDENTICALLY by the
# pre-compaction (r7, flat i32/bool) engine and the compacted engine —
# verified on both trees before pinning. Changing any of them requires a
# layout-version note here and in docs/state_layout.md.
# Layout-version r18: FIRE_KINDS growth (r17 remove/join, r18 disk_*)
# widened state.fires past the r8 11 columns these constants were
# hashed over; canonical_digest now hashes the r8 prefix contiguously
# and later columns only where nonzero (R8_FIRE_WIDTH above), which
# reproduces these EXACT r8 constants on the current engine — verified
# column-for-column before restoring. The trajectories never changed;
# the digest function had silently started hashing new zero columns.
GOLDEN = {
    "raft": "2a0e81ea9e273a54298b0bc11e44f377ef8861607ad320278695700bf0df861b",
    "paxos": "b32a304d0682bcc183b4b3d1382816bb6187c74d8f145d082e0198dec44efa8b",
    "kv": "2249bd64d3fd1aac94376125169167e7ae6f35fea51dfa06c0db38453ba58c9c",
    "twopc": "38b8eae7cd3944363dcac58cda088791727370d2892a28c8b978ab80c57a1666",
    "chain": "c6e860898bca578503460a96d3fdd9d9a21b7ea7b17313c0e4fd10ab785d1f86",
}


# the FIRE_KINDS prefix width at bless time (layout-version r8): the
# first 11 fire columns hash as one contiguous block, bit-compatible
# with the original pinned constants; columns ADDED by later clause
# families (r17 remove/join, r18 disk_*) enter the digest — named by
# kind — only where nonzero, so a run in which a later clause is absent
# digests identically to one on a tree where the clause doesn't exist.
# Widening FIRE_KINDS therefore never re-blesses GOLDEN by itself; only
# a trajectory change does.
R8_FIRE_WIDTH = 11


def canonical_digest(state) -> str:
    """Layout-independent trajectory digest: every field widened to i64,
    packed planes unpacked, narrow node leaves included as their VALUES
    (so any value-corrupting narrowing changes the digest, but a pure
    storage change cannot). Fire columns past the r8 width count only
    when nonzero (see R8_FIRE_WIDTH) — clause-family growth keeps old
    digests stable wherever the new clause is off."""
    h = hashlib.sha256()
    for name in ("clock", "epoch", "key", "done", "violated",
                 "violation_step", "steps", "events", "overflow",
                 "dead_drops", "crashed", "partitioned", "timer",
                 "alive", "link_ok"):
        h.update(np.ascontiguousarray(
            np.asarray(getattr(state, name)).astype(np.int64)))
    for leaf in jax.tree_util.tree_leaves(state.node):
        h.update(np.ascontiguousarray(np.asarray(leaf).astype(np.int64)))
    for part in (state.msgs.valid, state.msgs.deliver, state.msgs.kind,
                 state.msgs.payload):
        h.update(np.ascontiguousarray(np.asarray(part).astype(np.int64)))
    fires = np.asarray(state.fires).astype(np.int64)
    h.update(np.ascontiguousarray(fires[:, :R8_FIRE_WIDTH]))
    for i in range(R8_FIRE_WIDTH, fires.shape[1]):
        if fires[:, i].any():
            h.update(nemesis.FIRE_KINDS[i].encode())
            h.update(np.ascontiguousarray(fires[:, i]))
    return h.hexdigest()


def _golden_one(name):
    cfg = tpu_nemesis.compile_plan(CHAOS_PLAN, SimConfig(horizon_us=30_000_000))
    sim = BatchedSim(SPECS[name](), cfg)
    st = sim.run(jnp.arange(16, dtype=jnp.uint32), max_steps=1500,
                 dispatch_steps=1500)
    assert canonical_digest(st) == GOLDEN[name], (
        f"{name}: golden trajectory digest changed — if this narrowing/"
        "layout change is intentional, re-bless with a layout-version "
        "note (see module docstring)"
    )
    # the digest must describe a real run, not an idle one
    assert summarize(st)["total_events"] > 0


@pytest.mark.chaos
def test_golden_digest_raft():
    _golden_one("raft")


@pytest.mark.deep
@pytest.mark.chaos
@pytest.mark.parametrize("name", ["paxos", "kv", "twopc", "chain"])
def test_golden_digest_rest(name):
    _golden_one(name)
