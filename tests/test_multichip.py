"""Multi-chip fuzz fleet (r10, docs/multichip.md): shard_map'd refill
sweeps, the island-model explorer federation, and the device-aware
campaign farm.

The contract under test at every layer is the one the single-chip refill
engine already pinned (r9), lifted to the mesh: per-admission results
are a pure function of (admission order, seeds) — BIT-IDENTICAL across
device counts (1-device refill, 8-device shard_map'd refill, and the
chunked path all agree row-for-row), with zero cross-device collectives
inside the step (gathers at segment end only; `make analyze` walks the
sharded segment program for collective primitives). On top of that:
per-device occupancy >= 0.9 and >= 6x aggregate lane-step scaling at 8
devices on the 10x horizon-spread mix, the federation fingerprint
pinned across device counts and kill/resume, ddmin bundles identical
with and without a mesh, and `campaign serve` draining >= 3 concurrent
campaigns across devices with per-campaign bit-identical resume.

The fast (`chaos and not slow`) subset here IS the CI multichip smoke
(`make multichip-smoke`, <60s warm on the virtual 8-device mesh the
suite conftest forces).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu import nemesis
from madsim_tpu.tpu import make_raft_spec
from madsim_tpu.tpu import nemesis as tpu_nemesis
from madsim_tpu.tpu.batch import BatchWorkload, run_batch
from madsim_tpu.tpu.engine import (
    BatchedSim,
    TriageCtl,
    refill_results,
    refill_results_sharded,
)
from madsim_tpu.tpu.spec import REBASE_US, SimConfig

pytestmark = pytest.mark.chaos

PLAN = nemesis.FaultPlan(
    name="multichip-tests",
    clauses=(
        nemesis.Crash(interval_lo_us=150_000, interval_hi_us=450_000,
                      down_lo_us=100_000, down_hi_us=300_000),
        nemesis.Partition(interval_lo_us=200_000, interval_hi_us=600_000,
                          heal_lo_us=150_000, heal_hi_us=450_000),
        nemesis.MsgLoss(rate=0.05),
    ),
)
HORIZON = 1_000_000
CFG = tpu_nemesis.compile_plan(PLAN, SimConfig(horizon_us=HORIZON))

# the per-admission rows the cross-device determinism contract covers
# (`retired` is scheduling metadata — the global sweep step at
# retirement legitimately differs between queue partitionings, exactly
# as it differs between the refill and chunked paths)
ROW_FIELDS = (
    "violated", "deadlocked", "violation_at", "violation_epoch",
    "violation_step", "steps", "events", "overflow", "dead_drops",
    "clock", "epoch", "fires", "occ_fired",
)


def _mesh(n: int):
    devs = jax.devices()
    assert len(devs) >= n, "suite conftest forces an 8-device CPU mesh"
    return jax.sharding.Mesh(np.array(devs[:n]), ("seeds",))


@pytest.fixture(scope="module")
def tsim():
    return BatchedSim(make_raft_spec(), CFG, triage=True, coverage=True)


def _spread_ctl(A: int, spread: int = 10, long_every: int = 4):
    h = np.where(
        np.arange(A) % long_every == 0, HORIZON, HORIZON // spread
    ).astype(np.int64)
    return TriageCtl(
        off=jnp.zeros((A,), jnp.int32),
        occ=jnp.zeros((A, 4), jnp.int32),
        rate_scale=jnp.ones((A, 3), jnp.float32),
        h_epoch=jnp.asarray((h // REBASE_US).astype(np.int32)),
        h_off=jnp.asarray((h % REBASE_US).astype(np.int32)),
    )


# ------------------------------------------------- engine bit-identity


def test_sharded_refill_rows_bit_identical_across_device_counts(tsim):
    """The matrix row the whole fleet rests on: the SAME admissions
    (triage ctl genomes with a 10x horizon spread, coverage on) through
    the 1-device refill engine and the 2- and 8-device shard_map'd
    engines produce bit-identical per-admission rows — seeds,
    violations, chaos fire/occurrence tensors, coverage bitmaps, and
    the admission-relative step rows all equal."""
    A, L = 40, 2
    seeds = np.arange(A, dtype=np.uint32)
    ctl = _spread_ctl(A)
    ref = refill_results(
        tsim.run_refill(seeds, lanes=L, max_steps=30_000, ctl=ctl)
    )
    for D in (2, 8):
        st = tsim.run_refill_sharded(
            seeds, lanes=L, mesh=_mesh(D), max_steps=30_000, ctl=ctl
        )
        res = refill_results_sharded(st, admissions=A)
        assert res["devices"] == D
        assert res["truncated"] == 0
        for f in ROW_FIELDS + ("cov_bitmap", "cov_hiwater",
                               "cov_transitions"):
            if ref[f] is None:
                continue
            np.testing.assert_array_equal(
                ref[f], res[f], err_msg=f"{D}-device row {f} != 1-device"
            )
        # every device really worked and harvested its own sub-queue
        assert len(res["per_device"]) == D
        assert all(p["busy_lane_steps"] > 0 for p in res["per_device"])


def test_run_batch_refill_explicit_mesh_honored(tsim):
    """REGRESSION (the r9 gap this PR closes): run_batch(refill=...,
    mesh=<explicit mesh>) used to drop the mesh silently. It must now
    be HONORED — the summary reports the mesh's device count and
    per-device occupancy, and every per-seed output equals the
    unsharded refill sweep's."""
    wl = BatchWorkload(spec=make_raft_spec(), config=CFG, max_steps=30_000)
    r1 = run_batch(range(24), wl, mesh=None, max_traces=0, refill=2,
                   coverage=True)
    r8 = run_batch(range(24), wl, mesh=_mesh(8), max_traces=0, refill=2,
                   coverage=True)
    assert r8.summary["n_devices"] == 8
    assert len(r8.summary["per_device_occupancy"]) == 8
    np.testing.assert_array_equal(r1.violated, r8.violated)
    np.testing.assert_array_equal(r1.violation_step, r8.violation_step)
    np.testing.assert_array_equal(r1.coverage.bitmap, r8.coverage.bitmap)
    for k in ("violations", "total_events", "coverage_bits",
              "fires_crash", "fires_partition", "fires_loss",
              "mean_steps"):
        assert r1.summary[k] == r8.summary[k], k


def test_sharded_refill_occupancy_and_scaling_bars():
    """The fleet's two headline numbers on the 10x horizon-spread mix
    (the CI smoke assertions): per-device occupancy >= 0.9 on EVERY
    device of the 8-device mesh, and aggregate lane-step throughput per
    sweep iteration >= 6x the 1-device number at equal per-device
    lanes (near-linear scaling, hardware-independent form)."""
    import sys

    bench_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benches",
    )
    sys.path.insert(0, bench_dir)
    try:
        import roofline as rl
    finally:
        sys.path.remove(bench_dir)
    out = rl.mesh_scaling(
        lanes=8, waves=32, virtual_secs=0.5, device_counts=(1, 8),
    )
    rows = {r["devices"]: r for r in out["rows"]}
    assert set(rows) == {1, 8}
    for occ in rows[8]["per_device_occupancy"]:
        assert occ >= 0.90, rows[8]
    assert rows[8]["scaling_vs_1dev"] >= 6.0, rows[8]


def test_sharded_truncated_count_excludes_tail_pad(tsim):
    """A seed count not divisible by the device count pads the last
    sub-queue with duplicates of admission 0; when the whole-sweep step
    budget bites, the aggregate `truncated` count must cover the
    STRIPPED admissions only (it is recomputed from the stripped
    `retired == -1` rows), never the pad duplicates."""
    A = 9  # D=8, Ad=2 -> 7 pad rows, all duplicates of admission 0
    seeds = np.arange(A, dtype=np.uint32)
    st = tsim.run_refill_sharded(
        seeds, lanes=1, mesh=_mesh(8), max_steps=30_000,
        ctl=_spread_ctl(A), total_steps=50,
    )
    res = refill_results_sharded(st, admissions=A)
    assert res["truncated"] == int((res["retired"] == -1).sum())
    assert res["truncated"] <= A, res["truncated"]
    assert res["truncated"] > 0  # the budget really bit mid-admission


def test_sharded_state_refused_by_plain_decoder(tsim):
    """Mis-pairing the decoders fails LOUDLY in both directions: the
    plain refill_results refuses a device-stacked state (it would
    fancy-index the device axis into garbage), and refill_results_sharded
    refuses a 1-device state."""
    seeds = np.arange(8, dtype=np.uint32)
    st8 = tsim.run_refill_sharded(
        seeds, lanes=2, mesh=_mesh(8), max_steps=2_000,
        ctl=_spread_ctl(8),
    )
    with pytest.raises(ValueError, match="refill_results_sharded"):
        refill_results(st8)
    st1 = tsim.run_refill(
        seeds, lanes=2, max_steps=2_000, ctl=_spread_ctl(8)
    )
    with pytest.raises(ValueError, match="leading device axis"):
        refill_results_sharded(st1)


# ------------------------------------------------------ triage / ddmin


def test_triage_chunked_shrink_refuses_mesh(tsim):
    """An explicitly-passed mesh is honored or refused loudly, never
    dropped (the r9 run_batch bug class): the chunked ddmin evaluator
    has no sharded form, so refill=False + mesh raises."""
    from madsim_tpu import triage

    wl = BatchWorkload(spec=make_raft_spec(), config=CFG, max_steps=1_000)
    sim = BatchedSim(make_raft_spec(), CFG, triage=True)
    with pytest.raises(ValueError, match="refill"):
        triage.shrink_seed(wl, 0, sim=sim, refill=False, mesh=_mesh(2))


def test_triage_shrink_bundle_identical_with_mesh(tsim):
    """ddmin generations ride the sharded path: a shrink whose refill
    generations run shard_map'd over the mesh produces the same minimal
    bundle (kept atoms, masks, bisected horizon, violation step) as the
    single-device shrink — verdicts are pure per-(seed, ctl) rows on
    any device."""
    from madsim_tpu import triage

    from test_refill import _restamp_workload

    wl = _restamp_workload()
    sim = BatchedSim(wl.spec, wl.config, triage=True)
    a = triage.shrink_seed(wl, 0, sim=sim, mesh=_mesh(8))
    b = triage.shrink_seed(wl, 0, sim=sim)
    assert a.kept_atoms == b.kept_atoms
    assert a.bundle.occ_off == b.bundle.occ_off
    assert a.bundle.violation_step == b.bundle.violation_step
    assert a.bundle.horizon_us == b.bundle.horizon_us


# --------------------------------------------------- island federation


def test_federation_fingerprint_pinned_across_device_counts(tsim):
    """The island-model federation is a pure function of one meta-seed:
    the SAME 4-island search run (a) as one shard_map'd dispatch per
    generation on a 4-device mesh, (b) island-by-island on the default
    device, fingerprints identically — device placement never touches
    the search."""
    from madsim_tpu.explore import Federation

    wl = BatchWorkload(spec=make_raft_spec(), config=CFG, max_steps=30_000)
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]), ("islands",))
    ra = Federation(
        wl, n_islands=4, meta_seed=7, lanes=8, exchange_every=2,
        mesh=mesh, sim=tsim,
    ).run(4)
    rb = Federation(
        wl, n_islands=4, meta_seed=7, lanes=8, exchange_every=2,
        mesh=None, sim=tsim,
    ).run(4)
    assert ra["sharded"] and not rb["sharded"]
    assert ra["fingerprint"] == rb["fingerprint"]
    # the exchange really ran and preserved the union (campaign.minimize
    # raises on any dropped bit; reaching here means it held)
    assert ra["exchanges"] and ra["exchanges"] == rb["exchanges"]
    # islands draw disjoint fresh-seed sub-queues (stride = n_islands)
    from madsim_tpu.explore import Explorer

    ex = Explorer(wl, meta_seed=1, lanes=4, first_seed=2, fresh_stride=4,
                  shrink_violations=False, sim=tsim)
    pop = ex._population(0)
    assert [c.seed for c in pop] == [2, 6, 10, 14]


def test_federation_kill_resume_bit_identical(tsim):
    """snapshot()/restore() across a JSON round trip: 2 + 2 generations
    with a kill at the boundary fingerprint identically to the
    uninterrupted 4-generation federation (per-island MetaRng counter
    cursors + the exchange log are the whole state)."""
    from madsim_tpu.explore import Federation

    wl = BatchWorkload(spec=make_raft_spec(), config=CFG, max_steps=30_000)

    def fed():
        return Federation(
            wl, n_islands=4, meta_seed=7, lanes=8, exchange_every=2,
            mesh=None, sim=tsim,
        )

    full = fed().run(4)["fingerprint"]
    fa = fed()
    fa.run(2)
    snap = json.loads(json.dumps(fa.snapshot()))
    fb = fed()
    fb.restore(snap)
    assert fb.run(2)["fingerprint"] == full


@pytest.mark.slow
def test_federation_coverage_dominates_single_island(tsim):
    """The federation bar: at EQUAL total lane budget, the 8-island
    federated coverage curve dominates (or ties) the 1-chip curve —
    the exchange merges what eight independent searches found, and
    minimize's asserted union invariant guarantees no merged bit is
    ever lost."""
    from madsim_tpu.explore import Explorer, Federation

    wl = BatchWorkload(spec=make_raft_spec(), config=CFG, max_steps=30_000)
    gens = 4
    fed = Federation(
        wl, n_islands=8, meta_seed=5, lanes=8, exchange_every=2,
        mesh=None, sim=tsim,
    )
    fed_bits = fed.run(gens)["coverage_bits"]
    single = Explorer(
        wl, meta_seed=5, lanes=64, shrink_violations=False, sim=tsim,
    ).run(gens)
    assert fed_bits >= single.coverage_bits, (
        fed_bits, single.coverage_bits,
    )


# ------------------------------------------------------- campaign farm


def test_serve_schedules_campaigns_across_devices_stub(tmp_path):
    """Device-aware time-slicing without touching a real device: three
    queued campaigns on a 4-device service land on three DIFFERENT
    devices (least-loaded placement), a request's "devices" pin is
    honored, an out-of-range pin is rejected loudly, and every slice
    line carries its device index."""
    from madsim_tpu import campaign
    from madsim_tpu.explore import ExploreReport

    d = str(tmp_path / "svc")
    os.makedirs(os.path.join(d, "queue"))

    class Stub:
        def __init__(self, cid):
            self.cid, self.generation, self.bugs = cid, 0, []

        def run(self, g):
            self.generation += g
            return ExploreReport(
                meta_seed=0, lanes=1, dispatches=1, coverage_curve=[1],
                corpus_curve=[1], violation_curve=[0], violations=[],
                coverage_bits=1, corpus_size=1, seeds_run=1,
                first_violation_dispatch=None, wall_s=0.0,
                device_dispatches=2, corpus_digest="00" * 32,
            )

        def checkpoint(self):
            os.makedirs(
                os.path.join(d, "campaigns", self.cid), exist_ok=True
            )

    def factory(request, campaign_dir, regression_dir, log):
        return Stub(request["id"])

    reqs = {
        "a": {"workload": "raft", "generations": 2},
        "b": {"workload": "raft", "generations": 2, "devices": [1]},
        "c": {"workload": "raft", "generations": 2, "devices": [2, 3]},
        "bad": {"workload": "raft", "generations": 1, "devices": [9]},
    }
    for name, req in reqs.items():
        with open(os.path.join(d, "queue", f"{name}.json"), "w") as f:
            json.dump(req, f)
    lines = []
    res = campaign.serve(
        d, slice_generations=1, max_rounds=4, idle_rounds=1,
        out=lambda s: lines.append(json.loads(s)), factory=factory,
        sleep=lambda s: None, devices=["d0", "d1", "d2", "d3"],
    )
    assert sorted(res["completed"]) == ["a", "b", "c"]
    assert res["devices"] == 4
    rejected = [l for l in lines if l.get("rejected")]
    assert len(rejected) == 1 and "out of range" in rejected[0]["rejected"]
    devmap = {}
    for l in lines:
        if "report" in l:
            devmap.setdefault(l["campaign"], set()).add(l["device"])
    assert devmap["a"] == {0}
    assert devmap["b"] == {1}  # pinned device set honored
    assert devmap["c"] <= {2, 3}
    # >= 3 campaigns ran CONCURRENTLY across devices in one round: all
    # three appear in the first round's slice lines
    first_round = [l["campaign"] for l in lines if "report" in l][:3]
    assert sorted(first_round) == ["a", "b", "c"]


@pytest.mark.slow
def test_serve_drains_three_real_campaigns_across_devices(tmp_path):
    """The farm e2e bar: `campaign serve` with a 3-device fleet drains
    three REAL concurrent campaigns (distinct meta-seeds), slicing each
    on its own device, with a kill + restart at a slice boundary — and
    every campaign's final fingerprint equals its uninterrupted
    single-device run (placement and preemption never touch results)."""
    from madsim_tpu import campaign
    from madsim_tpu.campaign import Campaign, build_workload
    from madsim_tpu.campaign import named_workload_ref

    d = str(tmp_path / "farm")
    os.makedirs(os.path.join(d, "queue"))
    gens = 2
    seeds = {"a": 1, "b": 2, "c": 3}
    for name, ms in seeds.items():
        with open(os.path.join(d, "queue", f"{name}.json"), "w") as f:
            json.dump({
                "workload": "raft", "virtual_secs": 0.5, "lanes": 8,
                "meta_seed": ms, "generations": gens, "shrink": False,
            }, f)
    devices = jax.devices()[:3]
    lines = []

    def run_serve(max_rounds):
        return campaign.serve(
            d, slice_generations=1, max_rounds=max_rounds, idle_rounds=1,
            out=lambda s: lines.append(json.loads(s)),
            sleep=lambda s: None, devices=devices,
        )

    run_serve(1)  # one slice each, then the service "dies"
    res = run_serve(4)  # restart: resumes from checkpoints, drains
    assert sorted(res["completed"]) == ["a", "b", "c"]
    finals = {}
    for l in lines:
        if "report" in l and l["remaining"] == 0:
            finals[l["campaign"]] = l["fingerprint"]
    assert set(finals) == {"a", "b", "c"}
    # slices really spread across the fleet
    used = {l["device"] for l in lines if "report" in l}
    assert len(used) == 3
    # uninterrupted single-device reference runs, same search identity
    for name, ms in seeds.items():
        ref_dir = str(tmp_path / f"ref-{name}")
        wl = build_workload(named_workload_ref("raft", 0.5, False))
        c = Campaign(
            wl, ref_dir, meta_seed=ms, lanes=8, shrink=False,
            workload_ref=named_workload_ref("raft", 0.5, False),
        )
        rep = c.run(gens)
        assert rep.fingerprint() == finals[name], name
