"""The exact per-key linearizability checker (tpu/linearize.py) and the
device-side watermark oracle (kv wm_rev/wm_t): together they close the two
r3 oracle gaps — histories that pass revision monotonicity but are not
linearizable, and staleness whose witness op was evicted by the history
ring (SURVEY §7 step 5 / BASELINE config #4)."""

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from madsim_tpu.tpu import BatchedSim, SimConfig
from madsim_tpu.tpu.kv import OP_READ, OP_WRITE, kv_workload, make_kv_spec
from madsim_tpu.tpu.linearize import Op, check_key_history, check_lane
from madsim_tpu.tpu.batch import run_batch


def W(tinv, trsp, val, rev, key=0, node=0):
    return Op(tinv=tinv, trsp=trsp, is_write=True, key=key, val=val, rev=rev,
              node=node)


def R(tinv, trsp, val, rev, key=0, node=0):
    return Op(tinv=tinv, trsp=trsp, is_write=False, key=key, val=val, rev=rev,
              node=node)


def test_sequential_history_linearizable():
    ops = [W(0, 1, 7, 1), R(2, 3, 7, 1), W(4, 5, 9, 2), R(6, 7, 9, 2)]
    ok, _, unmatched = check_key_history(ops)
    assert ok and unmatched == 0


def test_concurrent_reads_both_orders_linearizable():
    # two reads concurrent with a write may split across it
    ops = [W(0, 10, 7, 1), R(1, 9, 0, 0), R(2, 8, 7, 1)]
    ok, _, _ = check_key_history(ops)
    assert ok


def test_future_read_caught_despite_monotone_revisions():
    """The r3 oracle hole: a read that returns a write's value BEFORE that
    write was even invoked. Revisions are perfectly monotone in real time
    (read rev 2 comes after write rev 1; the rev-2 write comes last with
    the highest rev), so the device's pairwise check passes — only a real
    linearizability search rejects it."""
    ops = [
        W(0, 1, 7, 1),
        R(2, 3, 9, 2),  # observes value 9 ...
        W(5, 6, 9, 2),  # ... which is only written later
    ]
    ok, ce, _ = check_key_history(ops)
    assert not ok
    assert ce is not None


def test_stale_read_between_completed_writes_caught():
    # w(A) then w(B) complete sequentially; a later read returning A must
    # linearize before w(B) yet starts after it — non-linearizable
    ops = [W(0, 1, 7, 1), W(2, 3, 9, 2), R(4, 5, 7, 1)]
    ok, _, _ = check_key_history(ops)
    assert not ok


def test_read_of_unacked_write_excluded_not_flagged():
    # value 42 has no witness write (client timed out / ring evicted):
    # excluded from the search, counted, NOT a violation
    ops = [W(0, 1, 7, 1), R(2, 3, 42, 5)]
    ok, _, unmatched = check_key_history(ops)
    assert ok and unmatched == 1


def test_check_lane_on_real_sweep_histories():
    # a correct-kv sweep's recorded histories are linearizable, and the
    # checker actually consumes them (ops_checked > 0)
    wl = kv_workload(virtual_secs=2.0)
    sim = BatchedSim(wl.spec, wl.config)
    state = sim.run(jnp.arange(8), max_steps=4000)
    for lane in range(8):
        r = check_lane(state.node, lane)
        assert r["linearizable"], r
    assert sum(check_lane(state.node, i)["ops_checked"] for i in range(8)) > 0


def test_run_batch_runs_lane_check_and_reports_counts():
    wl = kv_workload(virtual_secs=2.0)
    result = run_batch(range(16), wl, repro_on_host=False, max_traces=0)
    assert result.summary.get("lane_check_histories_checked", 0) > 0
    assert result.summary.get("lane_check_violations", 0) == 0
    assert result.summary.get("lane_check_ops_checked", 0) > 0


def _crafted_kv_state(spec, n_lanes=1):
    node, timer = jax.vmap(
        jax.vmap(spec.init, in_axes=(0, 0)), in_axes=(0, None)
    )(jnp.zeros((n_lanes, spec.n_nodes), jnp.uint32),
      jnp.arange(spec.n_nodes, dtype=jnp.int32))
    return node


def test_watermark_catches_stale_read_after_ring_wrap():
    """The r3 coverage hole: the pairwise check only sees retained ring
    entries, so a stale read whose high-rev witness was EVICTED passed.
    The per-(node,key) watermark keeps the max-rev evidence forever."""
    spec = make_kv_spec(n_nodes=3, ops_capacity=4)
    node = _crafted_kv_state(spec)
    alive = jnp.ones((3,), jnp.bool_)
    ok = lambda n: bool(spec.check_invariants(
        jax.tree_util.tree_map(lambda x: x[0], n), alive, jnp.int32(10_000)
    ))

    # node 1's ring holds ONLY a stale read: key 0, rev 3, invoked at
    # t=2000 — no other ring entry anywhere (the rev-50 write that makes it
    # stale was evicted long ago). Pairwise evidence alone cannot object.
    # The r5 oracle is INCREMENTAL (an op is checked when it ACKS, via the
    # la_* register the handler writes alongside the ring entry), so the
    # crafted state models the ack: ring entry + register together.
    node = node._replace(
        h_kind=node.h_kind.at[0, 1, 0].set(OP_READ),
        h_key=node.h_key.at[0, 1, 0].set(0),
        h_val=node.h_val.at[0, 1, 0].set(7),
        h_rev=node.h_rev.at[0, 1, 0].set(3),
        h_tinv=node.h_tinv.at[0, 1, 0].set(2_000),
        h_trsp=node.h_trsp.at[0, 1, 0].set(2_100),
        h_len=node.h_len.at[0, 1].set(9),  # wrapped: 9 > OPS=4
        la_kind=node.la_kind.at[0, 1].set(OP_READ),
        la_key=node.la_key.at[0, 1].set(0),
        la_val=node.la_val.at[0, 1].set(7),
        la_rev=node.la_rev.at[0, 1].set(3),
        la_tinv=node.la_tinv.at[0, 1].set(2_000),
        la_trsp=node.la_trsp.at[0, 1].set(2_100),
    )
    assert ok(node)  # without the watermark evidence, nothing to object to

    # node 0 acked rev 50 on key 0 at t=1000 (the op itself evicted; only
    # the watermark survives). The read invoked at 2000 with rev 3 is now
    # provably stale.
    stale = node._replace(
        wm_rev=node.wm_rev.at[0, 0, 0].set(50),
        wm_t=node.wm_t.at[0, 0, 0].set(1_000),
    )
    assert not ok(stale)

    # same watermark but established AFTER the read's invocation: the read
    # may legitimately linearize before it — no violation
    later = node._replace(
        wm_rev=node.wm_rev.at[0, 0, 0].set(50),
        wm_t=node.wm_t.at[0, 0, 0].set(2_050),
    )
    assert ok(later)


def test_watermark_tracks_acked_ops_in_sweep():
    # after a real sweep, watermarks reflect acked writes (nonzero), and a
    # correct protocol violates nothing
    wl = kv_workload(virtual_secs=2.0)
    sim = BatchedSim(wl.spec, wl.config)
    state = sim.run(jnp.arange(8), max_steps=4000)
    assert int(np.asarray(state.node.wm_rev).max()) > 0
    assert int(np.asarray(state.violated).sum()) == 0


def test_future_read_passes_device_oracle_but_not_wing_gong():
    """The exact checker earns its keep (VERDICT r4 weak #3): a READ that
    observes a value BEFORE the write producing it even started — with a
    monotone, unclaimed revision — satisfies every device invariant
    (monotonicity, coherence, watermarks) yet is not linearizable. Only
    the Wing-Gong search catches it."""
    from madsim_tpu.tpu import linearize

    spec = make_kv_spec(n_nodes=3, ops_capacity=4)
    node = _crafted_kv_state(spec)
    alive = jnp.ones((3,), jnp.bool_)

    def put(nd, n, i, kind, key, val, rev, tinv, trsp, register=False):
        nd = nd._replace(
            h_kind=nd.h_kind.at[0, n, i].set(kind),
            h_key=nd.h_key.at[0, n, i].set(key),
            h_val=nd.h_val.at[0, n, i].set(val),
            h_rev=nd.h_rev.at[0, n, i].set(rev),
            h_tinv=nd.h_tinv.at[0, n, i].set(tinv),
            h_trsp=nd.h_trsp.at[0, n, i].set(trsp),
            h_len=nd.h_len.at[0, n].add(1),
        )
        if register:
            nd = nd._replace(
                la_kind=nd.la_kind.at[0, n].set(kind),
                la_key=nd.la_key.at[0, n].set(key),
                la_val=nd.la_val.at[0, n].set(val),
                la_rev=nd.la_rev.at[0, n].set(rev),
                la_tinv=nd.la_tinv.at[0, n].set(tinv),
                la_trsp=nd.la_trsp.at[0, n].set(trsp),
            )
        return nd

    OP_WRITE = 2
    # node 0: the FUTURE READ — observes val 200001 at [1000, 1100], rev 7
    node = put(node, 0, 0, OP_READ, 0, 200001, 7, 1_000, 1_100, register=True)
    # node 2: the witness write of val 200001 happens LATER [5000, 5200],
    # rev 9 (revs stay monotone in real time; rev 7 is an unclaimed gap)
    node = put(node, 2, 0, OP_WRITE, 0, 200001, 9, 5_000, 5_200, register=True)
    node = node._replace(
        wm_rev=node.wm_rev.at[0, 0, 0].set(7),
        wm_t=node.wm_t.at[0, 0, 0].set(1_100),
    )

    # the device-side net passes it...
    assert bool(spec.check_invariants(
        jax.tree_util.tree_map(lambda x: x[0], node), alive, jnp.int32(9_000)
    ))
    # ...the exact checker does not
    verdict = linearize.check_lane(node, 0)
    assert not verdict["linearizable"], verdict


@pytest.mark.deep
def test_exact_checker_over_thousand_clean_lanes():
    """Deep tier: the exact Wing-Gong oracle over >= 1k clean lanes of a
    real partitioned sweep — with the horizon-sized ring nearly every
    acked op is ring-resident, so the exact check covers close to the
    full history (not the r4 ~0.1% sample)."""
    from madsim_tpu.tpu import linearize

    wl = kv_workload(virtual_secs=6.0)
    sim = BatchedSim(wl.spec, wl.config)
    lanes = 1024
    state = sim.run(jnp.arange(lanes), max_steps=10_000)
    assert int(np.asarray(state.violated).sum()) == 0
    out = linearize.check_lanes(state.node, range(lanes))
    assert out["violations"] == 0
    acked = float(np.asarray(state.node.h_len).sum())
    fraction = out["ops_checked"] / max(acked, 1)
    # horizon-sized ring: the exact check must cover the great majority
    # of every acked op, not a sliver
    assert fraction > 0.9, (out, acked)
