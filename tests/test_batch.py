"""run_batch bridge tests: a whole MADSIM_TEST_NUM sweep as ONE device batch,
with violating seeds reproduced on the single-lane host runtime.

This is the promised host<->TPU bridge (SURVEY.md §7 step 2; replaces the
reference's thread-per-seed fan-out, runtime/builder.rs:118-136)."""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import madsim_tpu as ms
from madsim_tpu.tpu.spec import replace_handlers
from madsim_tpu.tpu import (
    BatchViolation,
    BatchWorkload,
    SimConfig,
    batch_test,
    make_raft_spec,
    raft_workload,
    run_batch,
)
from madsim_tpu.tpu import raft as raft_mod


def buggy_raft_spec(n_nodes=5):
    """Raft with an injected split-brain bug: 2 of 5 votes win an election."""
    spec = make_raft_spec(n_nodes)

    def buggy_on_message(s, nid, src, kind, payload, now, key):
        state, out, timer = spec.on_message(s, nid, src, kind, payload, now, key)
        votes = jax.lax.population_count(state.votes.astype(jnp.uint32)).astype(
            jnp.int32
        )
        win = (state.role == raft_mod.CANDIDATE) & (votes >= 2) & (
            kind == raft_mod.VOTE_RESP
        )
        role = jnp.where(win, raft_mod.LEADER, state.role)
        return state._replace(role=role), out, jnp.where(win, now, timer)

    return replace_handlers(spec, on_message=buggy_on_message)


def test_clean_raft_sweep_no_violations():
    wl = raft_workload(virtual_secs=2.0)
    result = run_batch(range(64), wl)
    assert result.violations == 0
    result.raise_on_violation()  # no-op
    assert result.summary["total_events"] > 0


@pytest.mark.deep
def test_violating_seeds_reported_with_repro_seed():
    wl = raft_workload(virtual_secs=5.0, spec=buggy_raft_spec())
    result = run_batch(range(128), wl, repro_on_host=False)
    assert result.violations > 0
    seeds = result.violating_seeds
    assert all(0 <= s < 128 for s in seeds)
    with pytest.raises(BatchViolation) as e:
        result.raise_on_violation()
    assert e.value.seeds == seeds
    assert f"MADSIM_TEST_SEED={seeds[0]}" in str(e.value)


@pytest.mark.deep
def test_chunked_sweep_matches_single_batch():
    wl = raft_workload(virtual_secs=1.0, spec=buggy_raft_spec())
    a = run_batch(range(64), wl, repro_on_host=False)
    b = run_batch(range(64), wl, repro_on_host=False, chunk=16)
    assert a.violating_seeds == b.violating_seeds


@pytest.mark.deep
def test_violating_lane_reproduces_on_host_runtime():
    # TPU face finds the seed; host face re-runs it with full debugging.
    # The injected bug lives in the TPU spec only, so use the host face as a
    # sanity companion (it runs the REAL protocol: returns its own report).
    wl = raft_workload(virtual_secs=2.0, spec=buggy_raft_spec())
    result = run_batch(range(64), wl, max_host_repros=1)
    assert result.violations > 0
    assert len(result.host_repros) == 1
    (seed, repro), = result.host_repros.items()
    assert seed == result.violating_seeds[0]
    # the host reproducer ran a full simulation of that seed
    assert isinstance(repro, dict) and repro["events"] > 0


def test_batch_test_decorator_reads_env(monkeypatch):
    monkeypatch.setenv("MADSIM_TEST_SEED", "100")
    monkeypatch.setenv("MADSIM_TEST_NUM", "32")
    seen = {}

    @batch_test(raft_workload(virtual_secs=1.0))
    def my_test(result):
        seen["seeds"] = result.seeds

    my_test()
    assert seen["seeds"].tolist() == list(range(100, 132))


@pytest.mark.deep
def test_batch_test_decorator_raises_on_violation(monkeypatch):
    monkeypatch.setenv("MADSIM_TEST_NUM", "64")

    @batch_test(raft_workload(virtual_secs=5.0, spec=buggy_raft_spec()))
    def my_test(result):
        raise AssertionError("should not reach the body")

    with pytest.raises(BatchViolation):
        my_test()


def test_runtime_run_batch_entry_point():
    result = ms.Runtime.run_batch(range(16), raft_workload(virtual_secs=1.0))
    assert result.violations == 0


def test_batch_test_decorator_is_pytest_collectable():
    """pytest resolves fixture names from the wrapper's signature: the
    injected `result` parameter must not leak (it would demand a fixture
    named 'result' at collection time)."""
    import inspect

    @batch_test(raft_workload(virtual_secs=1.0))
    def my_test(result):
        pass

    assert not hasattr(my_test, "__wrapped__")
    assert "result" not in inspect.signature(my_test).parameters


def test_multi_device_sweep_bit_identical_to_single_device():
    """run_batch's production path uses EVERY visible device (the
    runtime/builder.rs:118-136 'use all the hardware' analog): on the test
    env's forced 8-CPU mesh, the auto-mesh sweep must produce bit-identical
    per-seed results to the unsharded run — lane-position-independent PRNG
    guarantees a seed's trajectory doesn't depend on device placement.
    Includes a non-divisible seed count (67 % 8 != 0) to cover the padding
    path, and a violating spec so the equality covers found bugs too."""
    assert len(jax.devices()) == 8  # conftest forces the virtual mesh
    wl = raft_workload(virtual_secs=2.0, spec=buggy_raft_spec())
    sharded = run_batch(range(67), wl, repro_on_host=False, max_traces=0)
    single = run_batch(range(67), wl, repro_on_host=False, max_traces=0,
                       mesh=None)
    assert sharded.summary["n_devices"] == 8
    assert single.summary["n_devices"] == 1
    assert np.array_equal(sharded.violated, single.violated)
    assert np.array_equal(sharded.deadlocked, single.deadlocked)
    for field in ("clock", "epoch", "steps", "events", "overflow"):
        a = np.asarray(getattr(sharded.state, field))
        b = np.asarray(getattr(single.state, field))
        assert np.array_equal(a, b), field
    assert sharded.violating_seeds == single.violating_seeds
    assert sharded.violations > 0  # the equality covered real findings


def test_check_determinism_mode():
    """The device analog of MADSIM_TEST_CHECK_DETERMINISM (rand.rs:63-111 /
    runtime/mod.rs:167-191): every chunk runs twice and the full final
    states must match bitwise; a fabricated divergence raises with the
    seed-range context."""
    from madsim_tpu.tpu.batch import (
        BatchDeterminismError,
        _assert_runs_bitwise_equal,
    )

    wl = raft_workload(virtual_secs=1.0)
    result = run_batch(range(24), wl, repro_on_host=False,
                       check_determinism=True)
    assert result.violations == 0

    # the comparison itself: any leaf divergence must raise
    state = result.state
    tweaked = state._replace(events=np.asarray(state.events) + 1)
    with pytest.raises(BatchDeterminismError, match="determinism check"):
        _assert_runs_bitwise_equal(state, tweaked, "seeds[0:24]")


def test_batch_test_decorator_check_determinism_env(monkeypatch):
    monkeypatch.setenv("MADSIM_TEST_NUM", "8")
    monkeypatch.setenv("MADSIM_TEST_CHECK_DETERMINISM", "1")

    @batch_test(raft_workload(virtual_secs=0.5))
    def inner(result):
        return result.violations

    assert inner() == 0


def test_fuzz_demo_example_runs():
    """examples/fuzz_demo.py end to end at a smoke-sized sweep: the planted
    bug is found, traced on device, and host-re-run."""
    import importlib
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))
    try:
        demo = importlib.import_module("fuzz_demo")
        demo.main(n_seeds=192)
    finally:
        sys.path.pop(0)


def test_batch_test_env_time_limit_and_config(monkeypatch, tmp_path):
    """builder.rs:55-107 env parity on the device path: TIME_LIMIT bounds
    virtual time (the horizon), CONFIG overlays SimConfig fields from
    TOML, and unknown fields fail loudly."""
    from madsim_tpu.tpu import abs_time_us

    monkeypatch.setenv("MADSIM_TEST_NUM", "8")
    monkeypatch.setenv("MADSIM_TEST_TIME_LIMIT", "0.5")
    cfg_file = tmp_path / "cfg.toml"
    cfg_file.write_text("loss_rate = 0.2\nlatency_hi_us = 5000\n")
    monkeypatch.setenv("MADSIM_TEST_CONFIG", str(cfg_file))

    @batch_test(raft_workload(virtual_secs=30.0))  # env must override 30 s
    def inner(result):
        t = abs_time_us(result.state)
        assert (t <= 1_500_000).all()  # ~0.5 s horizon, not 30 s
        return True

    assert inner()

    bad = tmp_path / "bad.toml"
    bad.write_text("not_a_field = 1\n")
    monkeypatch.setenv("MADSIM_TEST_CONFIG", str(bad))
    with pytest.raises(ValueError, match="unknown SimConfig"):
        inner()


def test_simconfig_validation_fails_loudly():
    from madsim_tpu.tpu import BatchedSim, SimConfig, make_raft_spec

    spec = make_raft_spec(5)
    with pytest.raises(ValueError, match="loss_rate"):
        BatchedSim(spec, SimConfig(loss_rate=1.5))
    with pytest.raises(ValueError, match="latency"):
        BatchedSim(spec, SimConfig(latency_lo_us=10_000, latency_hi_us=100))
    with pytest.raises(ValueError, match="horizon"):
        BatchedSim(spec, SimConfig(horizon_us=0))
    with pytest.raises(ValueError, match="msg_depth"):
        BatchedSim(spec, SimConfig(msg_depth_msg=0))


@pytest.mark.chaos
def test_planted_bug_found_and_harvested_on_owning_device(tmp_path):
    """VERDICT weak item (r10): a planted-bug seed (the raft deposed-
    leader re-stamp config) through the 8-device virtual mesh — the
    violation FIRES on the sharded refill sweep, the lane is harvested
    into the OWNING device's own RefillLog result buffers (the device
    whose sub-queue holds the admission), and the shrunk ReproBundle
    replays bit-identically on a single device."""
    from madsim_tpu import triage
    from madsim_tpu.repro import replay_device
    from madsim_tpu.tpu.engine import (
        BatchedSim,
        refill_results,
        refill_results_sharded,
    )

    from test_refill import _restamp_workload

    wl = _restamp_workload()
    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("seeds",))
    sim = BatchedSim(wl.spec, wl.config, triage=True)
    A, L = 32, 2
    seeds = np.arange(A, dtype=np.uint32)
    st = sim.run_refill_sharded(
        seeds, lanes=L, mesh=mesh, max_steps=wl.max_steps
    )
    res = refill_results_sharded(st, admissions=A)
    assert res["violated"].any(), "planted re-stamp bug must fire"
    a = int(np.nonzero(res["violated"])[0][0])

    # the admission was harvested on its OWNING device: sub-queues are
    # contiguous, so admission a lives on device a // Ad, and THAT
    # device's own RefillLog row (local index a % Ad) holds the harvest
    Ad = int(np.asarray(st.queue.seeds).shape[1])
    d = a // Ad
    dev_state = jax.tree_util.tree_map(lambda x: x[d], st)
    dev_rows = refill_results(dev_state)
    local = a - d * Ad
    assert bool(dev_rows["violated"][local])
    assert dev_rows["violation_step"][local] == res["violation_step"][a]
    assert int(np.asarray(st.queue.seeds)[d, local]) == a

    # ...and the per-admission row equals the unsharded refill row
    ref = refill_results(
        sim.run_refill(seeds, lanes=L, max_steps=wl.max_steps)
    )
    assert bool(ref["violated"][a])
    assert ref["violation_step"][a] == res["violation_step"][a]

    # shrink the violating seed into a ReproBundle and replay it
    # SINGLE-device: the violation must fire at the recorded step,
    # bit-identically across repeats (replay_device raises otherwise)
    sr = triage.shrink_seed(
        wl, a, sim=sim, out_dir=str(tmp_path), mesh=mesh,
    )
    assert sr.bundle.seed == a
    # (the shrunk plan's violation step is the MINIMAL plan's, not the
    # full plan's — replay_device asserts the bundle's own recorded
    # step/time fire bit-identically across repeats)
    report = replay_device(
        sr.bundle, spec=wl.spec, repeats=2, out=lambda *_: None,
    )
    assert report["violated"] and report["step"] == sr.bundle.violation_step
