"""Chain replication (the fifth device protocol) — the house test pattern
from docs/authoring_protocol_specs.md: safety under the chaos battery,
determinism, the planted canonical bug caught (on BOTH faces, and only
under the chaos class that exposes it), and host-twin wiring."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu.tpu import BatchedSim, chain_workload, make_chain_spec, summarize
from madsim_tpu.workloads import chain_host


def test_chain_safety_under_chaos_battery():
    wl = chain_workload(virtual_secs=5.0)
    sim = BatchedSim(wl.spec, wl.config)
    state = sim.run(jnp.arange(256), max_steps=30_000)
    s = summarize(state, wl.spec)
    assert s["violations"] == 0
    assert s["total_overflow"] == 0
    # progress: committed versions advance at the tail (a frozen fuzz
    # proves nothing)
    assert s["mean_committed_vers"] > 5


def test_chain_determinism():
    wl = chain_workload(virtual_secs=2.0)
    sim = BatchedSim(wl.spec, wl.config)
    a = sim.run(jnp.arange(32), max_steps=8_000)
    b = sim.run(jnp.arange(32), max_steps=8_000)
    for x, y in zip(
        __import__("jax").tree_util.tree_leaves(a.node),
        __import__("jax").tree_util.tree_leaves(b.node),
    ):
        assert (np.asarray(x) == np.asarray(y)).all()


def test_blind_apply_bug_caught_only_with_tails():
    """The canonical planted bug: a replica missing the apply-if-newer
    guard. Only heavy-tail stragglers (seconds-late duplicate forwards
    overtaking newer writes) expose it — the chaos class the buggify
    tail exists for."""
    wl = chain_workload(virtual_secs=8.0)
    buggy = make_chain_spec(5, buggy_blind_apply=True)

    # without tails: the 1-10 ms reorder window almost never lines up a
    # same-key duplicate — the bug hides
    state = BatchedSim(buggy, wl.config).run(jnp.arange(128), max_steps=40_000)
    quiet = summarize(state)["violations"]

    cfg = dataclasses.replace(
        wl.config, buggify_delay_rate=0.05, buggify_depth=8
    )
    state = BatchedSim(buggy, cfg).run(jnp.arange(128), max_steps=40_000)
    with_tails = summarize(state)["violations"]
    assert with_tails > quiet
    assert with_tails > 64  # the tail makes it near-certain

    # control: the correct spec is clean under the identical tails
    state = BatchedSim(wl.spec, cfg).run(jnp.arange(128), max_steps=40_000)
    assert summarize(state)["violations"] == 0


def test_chain_host_twin_clean_and_bug_on_both_faces():
    r = chain_host.fuzz_one_seed(3, virtual_secs=6.0)
    assert r["acked_ops"] > 20 and r["committed_max_ver"] > 0

    # host face: pinned violating seed (found by sweeping 0..11 — 3..8 hit)
    with pytest.raises(chain_host.InvariantViolation):
        chain_host.fuzz_one_seed(3, virtual_secs=10.0, tails=True, buggy=True)
    # the correct protocol is clean under the SAME tails and seed
    chain_host.fuzz_one_seed(3, virtual_secs=10.0, tails=True)

    # workload wiring: host_repro present and runs end to end
    out = chain_workload(virtual_secs=4.0).host_repro(5)
    assert out["violations"] == 0
