"""The static verifier verified: every rule fires on its planted fixture
and passes on the shipped tree (ISSUE 8).

Layer-1 rules are exercised twice: on deliberately broken toy step
programs under tests/fixtures/analysis/ (the rule FIRES) and on the real
raft step program traced abstractly (the rule passes) — the jaxpr smoke
reuses one small fixed lane width so the whole module stays seconds-fast
(tracing only; nothing compiles, nothing touches a device). Layer-2
source rules run against planted source fixtures and the live tree."""

import importlib.util
import json
import os
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import pytest

from madsim_tpu import analysis
from madsim_tpu.analysis import lint
from madsim_tpu.analysis.jaxpr_check import (
    LANES,
    check_callbacks,
    check_dtype,
    check_lane_independence,
    check_rng_taint,
    check_run_carry,
    check_step_donation,
    verify_workload,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "analysis")


def _load_toys():
    spec = importlib.util.spec_from_file_location(
        "analysis_toy_steps", os.path.join(FIXTURES, "toy_steps.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


toys = _load_toys()


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


# ----------------------------------------------------------- rule: callbacks


def test_callbacks_rule_fires_on_planted_callback():
    closed = jax.make_jaxpr(toys.callback_step)(_sds((LANES,), jnp.float32))
    res = check_callbacks(closed, "toy")
    assert not res.ok
    assert any("debug" in v.detail for v in res.violations)


def test_callbacks_rule_passes_clean():
    closed = jax.make_jaxpr(toys.clean_step)(_sds((LANES,), jnp.float32))
    assert check_callbacks(closed, "toy").ok


# ----------------------------------------------------------- rule: rng-taint


def test_rng_taint_fires_on_trajectory_coupled_schedule_draw():
    closed = jax.make_jaxpr(toys.impure_schedule_draw)(
        _sds((LANES,), jnp.uint32), _sds((LANES,), jnp.int32)
    )
    res = check_rng_taint(
        closed, ["const.key0", "hot.clock"], {"hot.clock"}, "toy"
    )
    assert not res.ok
    assert any("schedule-purity" in v.detail for v in res.violations)
    assert any("hot.clock" in v.detail for v in res.violations)


def test_rng_taint_witness_survives_inline_jit():
    """The mix eqns live inside a pjit sub-jaxpr; the violation must
    still fire AND name the offending leaf via the enclosing top-level
    equation."""
    closed = jax.make_jaxpr(toys.impure_draw_inside_jit)(
        _sds((LANES,), jnp.uint32), _sds((LANES,), jnp.int32)
    )
    res = check_rng_taint(
        closed, ["const.key0", "hot.clock"], {"hot.clock"}, "toy"
    )
    assert not res.ok
    assert any("hot.clock" in v.detail for v in res.violations), [
        v.render() for v in res.violations
    ]


def test_rng_taint_passes_occurrence_indexed_draw():
    closed = jax.make_jaxpr(toys.pure_schedule_draw)(
        _sds((LANES,), jnp.uint32), _sds((LANES,), jnp.int32)
    )
    res = check_rng_taint(
        closed, ["const.key0", "hot.nem.crash_k"], set(), "toy"
    )
    assert res.ok, [v.render() for v in res.violations]
    assert res.checked > 0  # the mixes were actually examined


def test_rng_taint_fires_on_contaminated_funnel():
    closed = jax.make_jaxpr(toys.contaminated_funnel)(
        _sds((LANES,), jnp.uint32), _sds((LANES, 3), jnp.int32)
    )
    res = check_rng_taint(
        closed, ["hot.key", "hot.msgs.payload"], set(), "toy",
        key_out_index=0,
    )
    assert not res.ok
    assert any("funnel" in v.detail for v in res.violations)


def test_rng_taint_passes_clean_funnel():
    closed = jax.make_jaxpr(toys.clean_funnel)(
        _sds((LANES,), jnp.uint32), _sds((LANES, 3), jnp.int32)
    )
    res = check_rng_taint(
        closed, ["hot.key", "hot.msgs.payload"], set(), "toy",
        key_out_index=0,
    )
    assert res.ok, [v.render() for v in res.violations]


# the refill toy signature: (key, key0, done, qseeds, cursor) — seeds are
# key ROOTS (the _init verification convention), the cursor is a neutral
# admission input, and `done` is a bool whose taint the control boundary
# strips
_REFILL_TOY_NAMES = [
    "hot.key", "hot.key0", "hot.done", "const.key0",
    "cold.refill.cursor",
]


def _refill_toy_args():
    return (
        _sds((LANES,), jnp.uint32), _sds((LANES,), jnp.uint32),
        _sds((LANES,), jnp.bool_), _sds((29,), jnp.uint32),
        _sds((), jnp.int32),
    )


def test_rng_taint_fires_on_leaky_refill():
    """The planted continuous-batching leak: a refilled lane's init
    folds a SURVIVOR'S running key chain into its new schedule root —
    its fault schedule then depends on how far other admissions happened
    to have run. rng-taint must flag the key0-rooted draw mixing chain
    (KEY2) material."""
    closed = jax.make_jaxpr(toys.leaky_refill)(*_refill_toy_args())
    res = check_rng_taint(closed, _REFILL_TOY_NAMES, set(), "toy")
    assert not res.ok
    assert any("schedule-purity" in v.detail for v in res.violations)


def test_rng_taint_passes_clean_refill():
    """The legal refill twin: new chain roots derive from the admitted
    queue seed alone (exactly a fresh lane's _init draw); the
    retirement mask is control, not value material."""
    closed = jax.make_jaxpr(toys.clean_refill)(*_refill_toy_args())
    res = check_rng_taint(closed, _REFILL_TOY_NAMES, set(), "toy")
    assert res.ok, [v.render() for v in res.violations]
    assert res.checked > 0


# the device-loop toy signature: (key, meta_key, counter, ring_seed,
# ring_n, done) — the ring's seed column is a key ROOT (the same _init
# verification convention as the refill queue's seed column), the
# MetaRng cursor and ring row count are neutral schedule-root inputs
# (jaxpr_check.DEVLOOP_NEUTRAL), and `done` is control material
_DEVLOOP_TOY_NAMES = [
    "hot.key", "cold.loop.meta_key", "cold.loop.counter", "const.key0",
    "cold.loop.ring_n", "hot.done",
]


def _devloop_toy_args():
    return (
        _sds((LANES,), jnp.uint32), _sds((), jnp.uint32),
        _sds((), jnp.int32), _sds((7,), jnp.uint32),
        _sds((), jnp.int32), _sds((LANES,), jnp.bool_),
    )


def test_rng_taint_fires_on_leaky_ring():
    """The planted device-loop leak (r19): the corpus-ring scatter folds
    a SURVIVOR LANE'S running key chain into a stored seed — every
    mutant descended from that ring row then runs a fault schedule that
    depends on how far other lanes happened to have run. rng-taint must
    flag the ring-rooted draw mixing chain (KEY2) material."""
    closed = jax.make_jaxpr(toys.leaky_ring)(*_devloop_toy_args())
    res = check_rng_taint(closed, _DEVLOOP_TOY_NAMES, set(), "toy")
    assert not res.ok
    assert any("schedule-purity" in v.detail for v in res.violations)


def test_rng_taint_passes_clean_devloop_ring():
    """The legal twin: the mutant root derives from the ring parent's
    seed alone, picked by a MetaRng draw off the (neutral) meta cursor —
    survivors' chains never reach the ring."""
    closed = jax.make_jaxpr(toys.clean_devloop_ring)(*_devloop_toy_args())
    res = check_rng_taint(closed, _DEVLOOP_TOY_NAMES, set(), "toy")
    assert res.ok, [v.render() for v in res.violations]
    assert res.checked > 0


def _toy_mesh():
    import numpy as np

    return jax.sharding.Mesh(np.array(jax.devices()[:2]), ("devices",))


def test_collective_walk_fires_on_planted_psum():
    """The planted multi-chip leak: a psum inside the shard_map'd
    segment couples every device's rows to every other's — the
    lane-independence rule's collective walk must name the exact
    primitive."""
    from madsim_tpu.analysis.jaxpr_check import check_collectives

    mesh = _toy_mesh()
    x = jax.ShapeDtypeStruct((2, 4), jnp.int32)
    closed = jax.make_jaxpr(toys.leaky_sharded_segment(mesh))(x)
    res = check_collectives(closed, "toy")
    assert not res.ok
    assert any("psum" in v.detail for v in res.violations)
    assert res.rule == "lane-independence"


def test_collective_walk_passes_clean_sharded_segment():
    """The legal twin: per-device compute only — zero collectives. An
    exact-primitive allowlist entry (never wholesale) would also pass
    the planted psum, pinned here so the allowlist stays exact."""
    from madsim_tpu.analysis.jaxpr_check import check_collectives

    mesh = _toy_mesh()
    x = jax.ShapeDtypeStruct((2, 4), jnp.int32)
    closed = jax.make_jaxpr(toys.clean_sharded_segment(mesh))(x)
    res = check_collectives(closed, "toy")
    assert res.ok, [v.render() for v in res.violations]
    assert res.checked > 0
    leaky = jax.make_jaxpr(toys.leaky_sharded_segment(mesh))(x)
    allowed = check_collectives(leaky, "toy", allow=("psum",))
    assert allowed.ok  # exact-name allowlist is honored, nothing broader


# --------------------------------------------------------------- rule: dtype


def _fake_sim(narrow=None, time_fields=()):
    return SimpleNamespace(
        spec=SimpleNamespace(
            narrow_fields=narrow or {}, time_fields=tuple(time_fields)
        )
    )


def test_dtype_rule_fires_on_float_time_arithmetic():
    closed = jax.make_jaxpr(toys.time_f32_step)(_sds((LANES,), jnp.int32))
    res = check_dtype(
        closed, _fake_sim(), None, (None,), ["hot.timer"], "toy"
    )
    assert not res.ok
    assert any("float arithmetic" in v.detail for v in res.violations)


def test_dtype_rule_passes_integer_ppm_time_math():
    closed = jax.make_jaxpr(toys.time_int_step)(_sds((LANES,), jnp.int32))
    res = check_dtype(
        closed, _fake_sim(), None, (None,), ["hot.timer"], "toy"
    )
    assert res.ok, [v.render() for v in res.violations]


def test_dtype_rule_fires_on_widened_narrow_field():
    closed = jax.make_jaxpr(toys.clean_step)(_sds((LANES,), jnp.float32))
    hot = SimpleNamespace(
        node=SimpleNamespace(term=_sds((LANES, 5), jnp.uint16))
    )
    out = (
        SimpleNamespace(node=SimpleNamespace(term=_sds((LANES, 5), jnp.int32))),
    )
    res = check_dtype(
        closed, _fake_sim(narrow={"term": jnp.uint16}), hot, out,
        ["hot.x"], "toy",
    )
    assert not res.ok
    assert any("silently widened" in v.detail for v in res.violations)


# --------------------------------------------------- rule: lane-independence


def test_lane_rule_fires_on_cross_lane_reduction():
    closed = jax.make_jaxpr(toys.lane_coupled_step)(
        _sds((LANES, 5), jnp.float32)
    )
    res = check_lane_independence(closed, LANES, "toy")
    assert not res.ok
    assert any("cross-lane" in v.detail for v in res.violations)


def test_lane_rule_fires_on_rhs_and_transposed_contractions():
    # a lane contraction hides on the RHS operand of a matmul ...
    closed = jax.make_jaxpr(toys.lane_coupled_rhs_matmul)(
        _sds((5, LANES), jnp.float32), _sds((LANES, 5), jnp.float32)
    )
    assert not check_lane_independence(closed, LANES, "toy").ok
    # ... or behind a transpose that moves the lane axis off position 0
    closed = jax.make_jaxpr(toys.lane_coupled_transposed)(
        _sds((LANES, 5), jnp.float32)
    )
    assert not check_lane_independence(closed, LANES, "toy").ok


def test_lane_rule_passes_lane_local_reduction():
    closed = jax.make_jaxpr(toys.lane_local_step)(
        _sds((LANES, 5), jnp.float32)
    )
    assert check_lane_independence(closed, LANES, "toy").ok


# ------------------------------------------------------------ rule: donation


def test_donation_rule_fires_on_undonatable_carry_leaf():
    hot, cold, const = toys.toy_state()
    res = check_step_donation(
        toys.widened_toy_step, hot, cold, const,
        toys.HOT_NAMES, toys.COLD_NAMES, toys.CONST_NAMES, "toy",
    )
    assert not res.ok
    # widening hot.x leaves ONE i32 carry leaf without a matching output
    # buffer; jax assigns the surviving alias greedily, so either i32
    # leaf may be the one reported — what matters is that a carry leaf
    # lost its donation
    assert any(
        "NOT donated" in v.detail
        and ("hot.x" in v.detail or "cold.acc" in v.detail)
        for v in res.violations
    )


def test_donation_rule_passes_clean_toy_step():
    hot, cold, const = toys.toy_state()
    res = check_step_donation(
        toys.good_toy_step, hot, cold, const,
        toys.HOT_NAMES, toys.COLD_NAMES, toys.CONST_NAMES, "toy",
    )
    assert res.ok, [v.render() for v in res.violations]


def test_donation_rule_fires_on_const_leaking_into_while_carry():
    hot, cold, const = toys.toy_state()
    closed = jax.make_jaxpr(toys.leaky_toy_run)(hot, cold, const)
    res = check_run_carry(closed, hot, cold, const, "toy")
    assert not res.ok
    assert any("carry" in v.detail for v in res.violations)


def test_donation_rule_passes_clean_while_carry():
    hot, cold, const = toys.toy_state()
    closed = jax.make_jaxpr(toys.good_toy_run)(hot, cold, const)
    res = check_run_carry(closed, hot, cold, const, "toy")
    assert res.ok, [v.render() for v in res.violations]


# ----------------------------------------------------- rule: ambient-entropy


def test_entropy_rule_fires_on_planted_fixture():
    res = lint.check_entropy_file(os.path.join(FIXTURES, "entropy_bad.py"))
    assert len(res.violations) == 7, [v.render() for v in res.violations]
    hits = " ".join(v.detail for v in res.violations)
    for needle in ("time.time", "random.random", "np.random.rand",
                   "os.urandom", "npr.rand", "default_rng", "date.today"):
        assert needle in hits
    # the pragma'd urandom and perf_counter were allowed
    assert sum("urandom" in v.detail for v in res.violations) == 1
    assert "perf_counter" not in hits


def test_entropy_rule_passes_shipped_tree():
    res = lint.check_entropy()
    assert res.ok, [v.render() for v in res.violations]
    assert res.checked > 1000  # it actually walked the package


# ---------------------------------------------------------- rule: both-faces


def test_both_faces_rule_fires_on_extra_device_fold():
    fix = os.path.join(FIXTURES, "cov_faces_bad.py")
    res = lint.check_both_faces(engine_path=fix, mirror_path=fix)
    assert not res.ok
    hits = " ".join(v.detail for v in res.violations)
    assert "5" in hits and "4" in hits  # device 5 folds vs mirror 4
    assert any("COV_FIELDS" in v.where or "COV_FIELDS" in v.detail
               for v in res.violations)


def test_both_faces_rule_fires_on_substituted_field():
    """Counts agree (4 == 4) but the device face folds payload_crc where
    the registry names bucket — the sequence check must fire."""
    fix = os.path.join(FIXTURES, "cov_faces_subst.py")
    res = lint.check_both_faces(engine_path=fix, mirror_path=fix)
    assert not res.ok
    assert any(
        "payload_crc" in v.detail and "bucket" in v.detail
        for v in res.violations
    ), [v.render() for v in res.violations]


def test_both_faces_rule_passes_shipped_tree():
    res = lint.check_both_faces()
    assert res.ok, [v.render() for v in res.violations]


# -------------------------------------------------------------- rule: mirror


def test_mirror_rule_fires_on_unhandled_event_kind():
    from madsim_tpu import nemesis as nem

    broken = dict(nem.CLAUSE_EVENT_KINDS)
    broken["spike"] = ("spike_on", "spike_off", "spike_pulse")
    res = lint.check_mirror(event_kinds=broken)
    assert not res.ok
    assert any("spike_pulse" in v.detail for v in res.violations)


def test_mirror_rule_fires_on_unregistered_clause():
    from madsim_tpu import nemesis as nem

    partial = {
        k: v for k, v in nem.SCHEDULE_CLAUSES.items() if k != "clog"
    }
    res = lint.check_mirror(schedule_clauses=partial)
    assert not res.ok
    assert any("LinkClog" in v.detail for v in res.violations)


def test_mirror_rule_ignores_docstring_prose():
    """A kind surviving only in a docstring after its handler was deleted
    must NOT count as handled."""
    fake_driver = '\n'.join([
        "class NemesisDriver:",
        "    def install(self):",
        '        """applies skew and spike_on windows at install"""',
        "    def _apply(self, ev):",
        "        for k in ('crash', 'restart', 'split', 'heal', 'clog',",
        "                  'unclog', 'spike_on', 'spike_off'):",
        "            if ev.kind == k:",
        "                return",
    ])
    res = lint.check_mirror(driver_source=fake_driver)
    assert any("skew" in v.detail and "never handles" in v.detail
               for v in res.violations), [v.render() for v in res.violations]


def test_mirror_rule_fires_on_driver_missing_reconfig_path():
    """The r17 fixture: a NemesisDriver whose _apply handles every legacy
    kind (and assigns skew) but never the reconfig clause's remove/join —
    the host application path of the membership axis silently gone. The
    mirror rule must name BOTH halves of the missing window."""
    fake_driver = '\n'.join([
        "class NemesisDriver:",
        "    def install(self):",
        "        self._assign('skew')",
        "    def _apply(self, ev):",
        "        for k in ('crash', 'restart', 'split', 'heal', 'clog',",
        "                  'unclog', 'spike_on', 'spike_off'):",
        "            if ev.kind == k:",
        "                return",
    ])
    res = lint.check_mirror(driver_source=fake_driver)
    assert not res.ok
    missing = [v for v in res.violations if "never handles" in v.detail]
    assert any("'remove'" in v.detail for v in missing), (
        [v.render() for v in res.violations]
    )
    assert any("'join'" in v.detail for v in missing)


def test_mirror_rule_fires_on_clause_without_host_coin_methods():
    """Face (f): a message clause with no HOST_COIN_METHODS entry is a
    FaultPlan clause whose host draws the oracle cannot verify."""
    from madsim_tpu import nemesis as nem

    partial = {
        k: v for k, v in nem.HOST_COIN_METHODS.items() if k != "reorder"
    }
    res = lint.check_mirror(host_coin_methods=partial)
    assert not res.ok
    assert any(
        "reorder" in v.detail and "not schedule-matched" in v.detail
        for v in res.violations
    ), [v.render() for v in res.violations]


def test_mirror_rule_fires_when_net_layer_never_draws():
    """Face (f): a registered draw method the net layer never calls means
    that clause's host face fell back to the ambient rng."""
    res = lint.check_mirror(net_source="x = 1\n")
    assert not res.ok
    assert any(
        "never called" in v.detail and "ambient rng" in v.detail
        for v in res.violations
    ), [v.render() for v in res.violations]


def test_mirror_rule_fires_when_oracle_ignores_the_registry():
    """Face (f): oracle.py must consume HOST_COIN_METHODS itself, or a
    new clause could ship on three faces without a comparator."""
    res = lint.check_mirror(oracle_source="pass\n")
    assert not res.ok
    assert any(
        "HOST_COIN_METHODS" in v.detail for v in res.violations
    ), [v.render() for v in res.violations]


def test_mirror_rule_fires_when_host_never_consumes_disk_coin():
    """Face (f), r18 half: `disk` is a SCHEDULE clause with a host coin
    (disk_torn_extent — the torn-tail extent FsSim keeps at a power
    fail). A driver+fs pair that handles every event kind but never
    touches the coin would silently UN-TEAR every scheduled torn crash
    on the host face; the mirror rule must catch that apply-path gap."""
    fake_driver = '\n'.join([
        "class NemesisDriver:",
        "    def install(self):",
        "        self._assign('skew')",
        "    def _apply(self, ev):",
        "        for k in ('crash', 'restart', 'split', 'heal', 'clog',",
        "                  'unclog', 'spike_on', 'spike_off', 'remove',",
        "                  'join', 'disk_slow', 'disk_crash',",
        "                  'disk_recover'):",
        "            if ev.kind == k:",
        "                return",
    ])
    res = lint.check_mirror(driver_source=fake_driver, fs_source="x = 1\n")
    assert not res.ok
    assert any(
        "disk_torn_extent" in v.detail and "un-tears" in v.detail
        for v in res.violations
    ), [v.render() for v in res.violations]


def test_mirror_rule_fires_on_stray_host_coin_entry():
    from madsim_tpu import nemesis as nem

    stray = dict(nem.HOST_COIN_METHODS)
    stray["jitter"] = ("loss",)
    res = lint.check_mirror(host_coin_methods=stray)
    assert not res.ok
    assert any("jitter" in v.detail for v in res.violations)


def test_mirror_rule_passes_shipped_registries():
    res = lint.check_mirror()
    assert res.ok, [v.render() for v in res.violations]


# ---------------------------------------------------- rule: layout-agreement


def test_layout_rule_fires_on_drifted_tables():
    res = lint.check_layout_agreement(
        narrow_fields={"bogus_field": jnp.uint8}
    )
    assert not res.ok
    assert any("bogus_field" in v.detail for v in res.violations)


def test_layout_rule_passes_shipped_tables():
    res = lint.check_layout_agreement()
    assert res.ok, [v.render() for v in res.violations]


# ------------------------------------------------------ rule: marker-hygiene


def test_marker_rule_fires_on_planted_unmarked_tests():
    res = lint.check_marker_hygiene_file(
        os.path.join(FIXTURES, "unmarked_slow_cases.py")
    )
    offenders = {v.detail.split()[0] for v in res.violations}
    assert offenders == {
        "test_soak_unmarked",
        "test_big_sweep_budgeted",
        # chaos does not exclude a test from the default run, so a
        # measured budget note still demands slow/deep
        "test_chaos_marked_but_budgeted",
    }, [v.render() for v in res.violations]


def test_marker_rule_passes_shipped_tests():
    res = lint.check_marker_hygiene()
    assert res.ok, [v.render() for v in res.violations]


# ------------------------------------------------- the real step program


def test_jaxpr_verifier_green_on_raft():
    """The foundation claim: the REAL raft step program (all nemesis
    clauses + triage + coverage, donated) satisfies every jaxpr rule.
    Abstract tracing only — the lane-width trick keeps this under a
    minute cold, seconds warm."""
    results = verify_workload("raft", log=None)
    bad = [v for r in results for v in r.violations]
    assert not bad, [v.render() for v in bad]
    by_rule = {r.rule for r in results}
    assert {"callbacks", "rng-taint", "dtype", "lane-independence",
            "donation"} <= by_rule
    # the rules saw real work: raft's step has >50 mix eqns and a
    # donated carry of dozens of leaves
    checked = {r.rule: 0 for r in results}
    for r in results:
        checked[r.rule] += r.checked
    assert checked["rng-taint"] > 50
    assert checked["donation"] > 30
    assert checked["lane-independence"] > 20


# ------------------------------------------------- shared traces + budget


def test_one_trace_per_workload_is_cached():
    """Perf satellite: every jaxpr rule (purity, taint, donation, dtype,
    lane, range) consumes ONE cached abstract trace per workload —
    re-requesting must return the same object, not re-trace."""
    from madsim_tpu.analysis.jaxpr_check import get_trace

    t1 = get_trace("raft", log=None)
    t2 = get_trace("raft", log=None)
    assert t1 is t2
    assert t1.closed_step is t2.closed_step
    assert len(t1.names) == len(t1.invars_avals)
    assert len(t1.out_names) == len(t1.closed_step.jaxpr.outvars)


@pytest.mark.slow
def test_full_analysis_all_stays_under_budget():
    """The --all acceptance bar: source lints + every jaxpr/range rule
    over all six trace targets (five workloads + raft's refill carry) in
    one process, sharing one trace per target, in well under 120 s on
    CPU (~45 s measured warm)."""
    import time

    t0 = time.perf_counter()
    summary = analysis.run_analysis(
        workloads=list(analysis.WORKLOADS), lint=True, log=None
    )
    wall = time.perf_counter() - t0
    assert summary["ok"] is True, summary["violation_details"]
    assert set(summary["certificates"]) == set(analysis.WORKLOADS) | {
        "_sum64"
    }
    assert wall < 120, f"--all took {wall:.0f}s (budget 120s)"


# ------------------------------------------------------------ summary + CLI


def test_summary_json_shape(tmp_path):
    summary = analysis.run_analysis(workloads=[], lint=True, log=None)
    assert summary["schema"] == analysis.SCHEMA
    assert summary["ok"] is True
    assert set(analysis.LINT_RULES) <= set(summary["rules"])
    for row in summary["rules"].values():
        assert row["status"] == "pass"
        assert row["violations"] == 0
    out = tmp_path / "analysis.json"
    analysis.write_summary(summary, str(out))
    assert json.loads(out.read_text())["ok"] is True


def test_empty_rule_set_is_not_a_pass():
    summary = analysis.run_analysis(workloads=[], lint=False, log=None)
    assert summary["ok"] is False  # zero rules ran: never green


def test_cli_lint_only_exits_zero(tmp_path):
    from madsim_tpu.analysis.__main__ import main

    out = tmp_path / "summary.json"
    rc = main(["--quiet", "--json", str(out)])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["ok"] is True and doc["workloads"] == []


def test_cli_rejects_zero_rule_invocation():
    from madsim_tpu.analysis.__main__ import main

    with pytest.raises(SystemExit) as exc:
        main(["--no-lint"])
    assert exc.value.code == 2  # argparse usage error, not a green exit
