"""Heavy-tail delay buggify (SimConfig.buggify_delay_rate): the
net/mod.rs:287-295 analog — a fraction of messages take SECONDS instead of
milliseconds. Extreme stragglers are a bug class uniform latency cannot
produce (they are why FoundationDB's buggify exists); the A/B test below
demonstrates one: an in-doubt 2PC participant that unilaterally aborts is
perfectly safe under <= 10 ms latencies and loses atomicity the moment an
OUTCOME rides the tail."""

import dataclasses
import pytest

import jax.numpy as jnp
import numpy as np

from madsim_tpu.tpu import BatchedSim, SimConfig, summarize
from madsim_tpu.tpu.spec import replace_handlers
from madsim_tpu.tpu import twopc as tp


def unilateral_abort_spec(n_nodes=5):
    """The canonical WRONG 2PC participant: when its in-doubt retry timer
    fires, it aborts the oldest unresolved yes-vote locally instead of
    asking the coordinator (cooperative termination skipped)."""
    spec = tp.make_twopc_spec(n_nodes)
    inner = spec.on_timer

    def on_timer(s, nid, now, key):
        state, out, timer = inner(s, nid, now, key)
        voted_yes = (s.v_tid >= 0) & (s.v_val == tp.COMMIT)
        resolved = (s.v_tid == s.o_tid) & (s.o_tid >= 0)
        doubt = voted_yes & ~resolved
        dreq_tid = jnp.where(doubt, s.v_tid, jnp.int32(2**30)).min()
        # only the NEWEST vote counts as "timed out" for this bug: ancient
        # ring-recycled doubts (a benign liveness wart — the coordinator's
        # outcome slot was reused, so a DREQ would go unanswered forever)
        # would trigger it even at microsecond latencies and drown the A/B
        in_doubt = (nid != 0) & doubt.any() & (dreq_tid == s.v_tid.max())
        # the bug: record a local ABORT for the txn instead of the DREQ
        at = jnp.arange(s.o_tid.shape[0]) == (dreq_tid % s.o_tid.shape[0])
        fresh = in_doubt & ~(at & (s.o_tid == dreq_tid)).any()
        w = at & fresh
        state = state._replace(
            o_tid=jnp.where(w, dreq_tid, state.o_tid),
            o_val=jnp.where(w, tp.ABORT, state.o_val),
        )
        # suppress the DREQ it would have sent (participant side only —
        # the coordinator's broadcasts must keep flowing)
        out = out._replace(valid=out.valid & ~in_doubt)
        return state, out, timer

    return replace_handlers(spec, on_timer=on_timer)


def quiet_config(**kw):
    """No loss, no crashes, no partitions: the ONLY chaos is whatever
    latency the buggify tail adds."""
    defaults = dict(
        horizon_us=10_000_000,
        loss_rate=0.0,
        msg_depth_msg=2,
        msg_depth_timer=2,
    )
    defaults.update(kw)
    return SimConfig(**defaults)


@pytest.mark.deep
def test_unilateral_abort_dormant_without_tail():
    # uniform 1-10 ms latency: the OUTCOME always lands long before the
    # 80 ms in-doubt retry, so the bug never fires — 0 violations
    sim = BatchedSim(unilateral_abort_spec(), quiet_config())
    state = sim.run(jnp.arange(128), max_steps=40_000)
    s = summarize(state, sim.spec)
    assert s["violations"] == 0, s


@pytest.mark.deep
def test_unilateral_abort_caught_only_by_heavy_tail():
    # same spec, same quiet network, plus a 5% 1-5 s delay tail: an OUTCOME
    # rides the tail, the yes-voter "times out" and aborts a committed txn,
    # and the atomicity invariant fires. This bug class is INVISIBLE to
    # uniform latency (see the dormant test above).
    sim = BatchedSim(
        unilateral_abort_spec(),
        quiet_config(buggify_delay_rate=0.05),
    )
    state = sim.run(jnp.arange(128), max_steps=40_000)
    s = summarize(state, sim.spec)
    assert s["violations"] > 0, s


@pytest.mark.deep
def test_correct_spec_survives_heavy_tail():
    # control: correct 2PC (cooperative termination) holds atomicity
    # through the same tail chaos
    sim = BatchedSim(
        tp.make_twopc_spec(5),
        quiet_config(buggify_delay_rate=0.05),
    )
    state = sim.run(jnp.arange(128), max_steps=40_000)
    s = summarize(state, sim.spec)
    assert s["violations"] == 0, s


def test_tail_messages_actually_ride_the_side_pool():
    sim = BatchedSim(tp.make_twopc_spec(5), quiet_config(buggify_delay_rate=0.1))
    assert sim._B > 0
    state = sim.init(jnp.arange(32))
    state = sim.run_steps(state, 400)
    # stragglers are in flight mid-run (1-5 s deliveries vs ms traffic)
    assert bool(np.asarray(state.strag.valid).any())
    # and their deliver times are seconds out, not milliseconds
    pend = np.asarray(state.strag.deliver)[np.asarray(state.strag.valid)]
    clock = np.asarray(state.clock).max()
    assert (pend > clock + 500_000).any()


def test_buggify_disabled_builds_no_side_pool():
    sim = BatchedSim(tp.make_twopc_spec(5), quiet_config())
    assert sim._B == 0
    state = sim.init(jnp.arange(4))
    assert state.strag is None


def test_buggify_composes_with_multi_device_mesh():
    """The straggler side pool must shard lane-only (its dim 1 is the
    candidate axis, not nodes) and stay bit-identical across mesh layouts."""
    import jax
    import dataclasses

    from madsim_tpu.tpu.batch import run_batch
    from madsim_tpu.tpu.twopc import twopc_workload

    wl = twopc_workload(virtual_secs=1.0)
    wl = dataclasses.replace(
        wl, config=dataclasses.replace(wl.config, buggify_delay_rate=0.1)
    )
    assert len(jax.devices()) == 8
    sharded = run_batch(range(16), wl, repro_on_host=False, max_traces=0)
    single = run_batch(range(16), wl, repro_on_host=False, max_traces=0,
                       mesh=None)
    assert sharded.summary["n_devices"] == 8
    assert np.array_equal(
        np.asarray(sharded.state.events), np.asarray(single.state.events)
    )
    assert np.array_equal(
        np.asarray(sharded.state.strag.valid),
        np.asarray(single.state.strag.valid),
    )


def test_cooperative_buggify_raft_leader_mute():
    """The spec-side cooperative fault hook (spec.buggify, the
    buggify.rs:8-32 analog): raft with leaders randomly going silent for
    a tick must still hold every safety invariant under partitions, and
    the fault point must actually perturb trajectories (same seeds, more
    elections than the unbuggified run)."""
    from madsim_tpu.tpu import make_raft_spec

    cfg = SimConfig(
        horizon_us=5_000_000,
        loss_rate=0.05,
        partition_interval_lo_us=400_000,
        partition_interval_hi_us=1_500_000,
        partition_heal_lo_us=500_000,
        partition_heal_hi_us=2_000_000,
    )
    plain = BatchedSim(make_raft_spec(5), cfg).run(
        jnp.arange(64), max_steps=30_000
    )
    bugged = BatchedSim(make_raft_spec(5, buggify_rate=0.25), cfg).run(
        jnp.arange(64), max_steps=30_000
    )
    assert summarize(plain)["violations"] == 0
    assert summarize(bugged)["violations"] == 0
    terms_plain = np.asarray(plain.node.term).max(axis=1)
    terms_bugged = np.asarray(bugged.node.term).max(axis=1)
    # silent leaders force re-elections: term churn must rise
    assert terms_bugged.mean() > terms_plain.mean() + 0.5, (
        terms_plain.mean(), terms_bugged.mean(),
    )
