"""The str-hash determinism hole, pinned (VERDICT r2 weak #4).

CPython randomizes the str hash seed per process; user code iterating a
str-keyed set inside a sim therefore draws RNG in a process-dependent order
— exactly the nondeterminism class the reference kills by seeding HashMap's
RandomState (rand.rs:176-244). Python can't re-seed str hashing at runtime,
so the framework (a) warns loudly at Runtime construction when the hash
seed is unpinned, and (b) the cross-process determinism check catches the
divergence — proven here by recording an RNG trace in one process and
replaying it in another with a different hash seed.
"""

import os
import subprocess
import sys
from pathlib import Path

REPO = str(Path(__file__).resolve().parent.parent)

# A sim whose RNG trace depends on str-set iteration order: before each
# draw the task sleeps a key-derived duration, so every draw's virtual-time
# annotation in the trace is the prefix sum of the iteration order — any
# reordering shifts the (value, vtime-hash) pairs and the replay diverges.
SCRIPT = """
import pickle, sys
sys.path.insert(0, {repo!r})
from madsim_tpu.core.rng import DeterminismError
from madsim_tpu.core.runtime import Runtime
from madsim_tpu.core.vtime import sleep

async def body():
    import random
    keys = {{f"key-{{i}}-{{'x' * (i % 7)}}" for i in range(32)}}
    out = []
    for k in keys:  # iteration order depends on the process hash seed
        await sleep((sum(k.encode()) % 97 + 1) / 1000)
        out.append(random.randrange(2 + sum(k.encode())))
    return out

mode, path = sys.argv[1], sys.argv[2]
rt = Runtime(seed=7)
if mode == "record":
    rt.enable_determinism_check()
    rt.block_on(body())
    Path = __import__("pathlib").Path
    Path(path).write_bytes(pickle.dumps(rt.take_rand_log()))
    print("RECORDED")
else:
    log = pickle.loads(__import__("pathlib").Path(path).read_bytes())
    rt.enable_determinism_check(log)
    try:
        rt.block_on(body())
    except DeterminismError:
        print("DIVERGED")
    else:
        print("MATCHED")
""".format(repo=REPO)


def _run(mode: str, log_path: str, hashseed: str | None) -> str:
    env = {k: v for k, v in os.environ.items() if k != "PYTHONHASHSEED"}
    if hashseed is not None:
        env["PYTHONHASHSEED"] = hashseed
    proc = subprocess.run(
        [sys.executable, "-c", SCRIPT, mode, log_path],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert proc.returncode == 0, proc.stderr
    return proc.stdout.strip().splitlines()[-1]


def test_unpinned_hash_caught_across_processes(tmp_path):
    log = str(tmp_path / "rand.log")
    assert _run("record", log, "12345") == "RECORDED"
    # a different hash seed reorders set iteration => the replay diverges
    assert _run("check", log, "54321") == "DIVERGED"


def test_pinned_hash_reproduces_across_processes(tmp_path):
    log = str(tmp_path / "rand.log")
    assert _run("record", log, "0") == "RECORDED"
    assert _run("check", log, "0") == "MATCHED"


def test_runtime_warns_on_unpinned_hash():
    probe = (
        "import sys, warnings\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "with warnings.catch_warnings(record=True) as w:\n"
        "    warnings.simplefilter('always')\n"
        "    from madsim_tpu.core.runtime import Runtime\n"
        "    Runtime(seed=1)\n"
        "print(sum('PYTHONHASHSEED' in str(x.message) for x in w))\n"
    )
    env = {k: v for k, v in os.environ.items() if k != "PYTHONHASHSEED"}
    out = subprocess.run(
        [sys.executable, "-c", probe], capture_output=True, text=True,
        timeout=120, env=env,
    )
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "1"  # warned, exactly once

    env["PYTHONHASHSEED"] = "0"
    out = subprocess.run(
        [sys.executable, "-c", probe], capture_output=True, text=True,
        timeout=120, env=env,
    )
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "0"  # pinned => silent

    # a pinned NONZERO seed is also cross-process reproducible: no warning
    # (sys.flags.hash_randomization is 1 here — the env var is ground truth)
    env["PYTHONHASHSEED"] = "12345"
    out = subprocess.run(
        [sys.executable, "-c", probe], capture_output=True, text=True,
        timeout=120, env=env,
    )
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "0"