"""Batched TPU engine tests (run on the virtual 8-device CPU mesh).

Mirrors the reference test strategy (SURVEY.md §4) for the batched backend:
protocol correctness as invariants over fuzzed executions, determinism as a
tested property, and bug-detection validated by injecting a known bug.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu.tpu.spec import replace_handlers
from madsim_tpu.tpu import (
    BatchedSim,
    SimConfig,
    make_raft_spec,
    summarize,
)
from madsim_tpu.tpu import prng
from madsim_tpu.tpu import raft as raft_mod


@pytest.fixture(scope="module")
def quiet_sim():
    return BatchedSim(make_raft_spec(5), SimConfig(horizon_us=2_000_000))


@pytest.fixture(scope="module")
def chaos_sim():
    return BatchedSim(
        make_raft_spec(5),
        SimConfig(
            horizon_us=3_000_000,
            loss_rate=0.1,
            crash_interval_lo_us=300_000,
            crash_interval_hi_us=1_500_000,
            restart_delay_lo_us=200_000,
            restart_delay_hi_us=800_000,
        ),
    )


def test_raft_elects_and_replicates(quiet_sim):
    state = quiet_sim.run(jnp.arange(8), max_steps=10_000)
    s = summarize(state)
    assert s["violations"] == 0
    assert s["deadlocked"] == 0
    roles = np.asarray(state.node.role)
    assert (np.sum(roles == raft_mod.LEADER, axis=1) == 1).all()  # one leader/lane
    commits = np.asarray(state.node.commit)
    assert (commits >= 0).all()  # every node committed something
    # committed window entries agree across nodes where windows overlap
    # (spot-check lane 0; full prefix agreement is the chain-hash invariant,
    # already asserted via violations == 0)
    cmds = np.asarray(state.node.log_cmd)[0]
    bases = np.asarray(state.node.base)[0]
    lo, hi = bases.max(), commits[0].min()
    for n in range(1, cmds.shape[0]):
        a = cmds[0][lo - bases[0] : hi + 1 - bases[0]]
        b = cmds[n][lo - bases[n] : hi + 1 - bases[n]]
        if hi >= lo:
            assert (a == b).all()


def test_chaos_run_no_violations(chaos_sim):
    state = chaos_sim.run(jnp.arange(32), max_steps=30_000)
    s = summarize(state)
    assert s["violations"] == 0
    # chaos actually happened: terms advanced beyond 1 somewhere
    assert np.asarray(state.node.term).max() >= 2


def test_determinism_same_seeds_same_trajectory(chaos_sim):
    a = chaos_sim.run(jnp.arange(16), max_steps=30_000)
    b = chaos_sim.run(jnp.arange(16), max_steps=30_000)
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        assert jnp.array_equal(x, y)


def test_different_seeds_diverge(chaos_sim):
    state = chaos_sim.run(jnp.arange(16), max_steps=30_000)
    events = np.asarray(state.events)
    assert len(set(events.tolist())) > 1  # lanes took different trajectories


def test_injected_bug_is_caught():
    spec = make_raft_spec(5)

    def buggy_on_message(s, nid, src, kind, payload, now, key):
        state, out, timer = spec.on_message(s, nid, src, kind, payload, now, key)
        votes = jax.lax.population_count(state.votes.astype(jnp.uint32)).astype(
            jnp.int32
        )
        # classic off-by-one: 2 votes of 5 "win" the election
        win = (state.role == raft_mod.CANDIDATE) & (votes >= 2) & (
            kind == raft_mod.VOTE_RESP
        )
        role = jnp.where(win, raft_mod.LEADER, state.role)
        return state._replace(role=role), out, jnp.where(win, now, timer)

    buggy = replace_handlers(spec, on_message=buggy_on_message)
    sim = BatchedSim(
        buggy,
        SimConfig(
            horizon_us=5_000_000,
            loss_rate=0.1,
            crash_interval_lo_us=300_000,
            crash_interval_hi_us=1_500_000,
        ),
    )
    state = sim.run(jnp.arange(64), max_steps=40_000)
    s = summarize(state)
    assert s["violations"] > 0  # the fuzzer finds the split-brain
    # violation report carries repro info
    lane = s["violation_lanes"][0]
    assert np.asarray(state.violation_at)[lane] < 2**31 - 1


def test_lane_sharding_over_mesh(chaos_sim):
    devices = np.array(jax.devices()[:8])
    mesh = jax.sharding.Mesh(devices, ("seeds",))
    state = chaos_sim.init(jnp.arange(16))
    state = chaos_sim.shard_state(state, mesh, lane_axis="seeds")
    out = chaos_sim._run(state, 200)
    jax.block_until_ready(out)
    # sharded run matches unsharded run exactly
    ref = chaos_sim._run(chaos_sim.init(jnp.arange(16)), 200)
    for x, y in zip(jax.tree_util.tree_leaves(out), jax.tree_util.tree_leaves(ref)):
        assert jnp.array_equal(jax.device_get(x), jax.device_get(y))


def test_2d_mesh_node_sharding():
    devices = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh = jax.sharding.Mesh(devices, ("seeds", "nodes"))
    sim = BatchedSim(
        make_raft_spec(n_nodes=8),
        SimConfig(horizon_us=500_000, loss_rate=0.05),
    )
    state = sim.init(jnp.arange(8))
    state = sim.shard_state(state, mesh, lane_axis="seeds", node_axis="nodes")
    out = sim._run(state, 100)
    jax.block_until_ready(out)
    assert int(out.events.sum()) > 0


def partition_config(**kw):
    defaults = dict(
        horizon_us=8_000_000,
        loss_rate=0.05,
        partition_interval_lo_us=300_000,
        partition_interval_hi_us=1_500_000,
        partition_heal_lo_us=500_000,
        partition_heal_hi_us=2_000_000,
    )
    defaults.update(kw)
    return SimConfig(**defaults)


def test_partition_chaos_raft_stays_safe():
    # correct Raft keeps Election Safety + Log Matching through repeated
    # random bipartitions (the network.rs:261-269 clog-link analog, batched)
    sim = BatchedSim(make_raft_spec(5), partition_config())
    state = sim.run(jnp.arange(64), max_steps=40_000)
    s = summarize(state)
    assert s["violations"] == 0
    # partitions actually happened and healed
    assert np.asarray(state.partitioned).any() or np.asarray(state.part_at).max() > 0
    assert np.asarray(state.node.term).max() >= 2  # elections churned


def test_partition_split_brain_bug_caught():
    # injected bug: a leader commits as soon as ONE follower acks (no
    # majority). Only a partition makes this fatal: a minority-side leader
    # keeps committing while the majority side elects a new leader and
    # commits different entries => committed-prefix divergence.
    spec = make_raft_spec(5, client_rate=0.8)

    def buggy_append_resp(s, nid, src, kind, payload, now, key):
        state, out, timer = spec.on_message(s, nid, src, kind, payload, now, key)
        is_ar = kind == raft_mod.APPEND_RESP
        success = payload[1] > 0
        match = payload[2]
        # any single ack advances commit (ignores the majority rule)
        bogus_commit = jnp.where(
            is_ar & success & (state.role == raft_mod.LEADER),
            jnp.maximum(state.commit, jnp.minimum(match, state.log_len - 1)),
            state.commit,
        )
        return state._replace(commit=bogus_commit), out, timer

    buggy = replace_handlers(spec, on_message=buggy_append_resp)

    # without partitions: the bug is mostly harmless in this horizon
    # with partitions: split-brain commits diverge and the fuzz catches it
    sim = BatchedSim(buggy, partition_config(loss_rate=0.1))
    state = sim.run(jnp.arange(256), max_steps=60_000)
    s = summarize(state)
    assert s["violations"] > 0


def test_log_compaction_unbounded_writes_through_bounded_window():
    """The VERDICT r2 weak-#2 fix: a lane writes far more commands than the
    window holds (compaction folds the committed prefix into a chain hash),
    and a crash-restarted laggard catches up via InstallSnapshot — all with
    zero saturated lanes and zero violations."""
    sim = BatchedSim(
        make_raft_spec(5, client_rate=0.8),
        SimConfig(
            horizon_us=6_000_000,
            loss_rate=0.05,
            crash_interval_lo_us=1_000_000,
            crash_interval_hi_us=2_000_000,
            restart_delay_lo_us=1_000_000,
            restart_delay_hi_us=2_000_000,
        ),
    )
    state = sim.run(jnp.arange(32), max_steps=60_000)
    s = summarize(state, sim.spec)
    assert s["violations"] == 0
    assert s["log_saturated_lanes"] == 0
    log_len = np.asarray(state.node.log_len)
    base = np.asarray(state.node.base)
    LOG = 24
    # most lanes wrote beyond the window capacity => compaction really ran
    assert (log_len.max(axis=1) > LOG).mean() > 0.8
    assert (base > 0).any()
    # crash victims caught back up (InstallSnapshot): by the horizon every
    # node's commit is near the lane's max in the vast majority of lanes
    commit = np.asarray(state.node.commit)
    caught_up = commit.min(axis=1) > (commit.max(axis=1) - LOG)
    assert caught_up.mean() > 0.7


def test_message_pool_overflow_counted():
    # tiny pool: heartbeat broadcasts overflow it, and the engine must count
    # drops instead of corrupting state
    sim = BatchedSim(
        make_raft_spec(5, heartbeat_us=5_000),
        SimConfig(horizon_us=500_000, msg_capacity=4),
    )
    state = sim.run(jnp.arange(4), max_steps=20_000)
    s = summarize(state)
    assert s["total_overflow"] > 0
    assert s["violations"] == 0


def test_prng_quality_rough():
    key = prng.key_from(jnp.arange(10_000, dtype=jnp.uint32))
    u = prng.uniform(key, 1)
    assert 0.48 < float(u.mean()) < 0.52
    assert float(u.min()) >= 0.0 and float(u.max()) < 1.0
    # distinct sites give decorrelated streams
    v = prng.uniform(key, 2)
    corr = np.corrcoef(np.asarray(u), np.asarray(v))[0, 1]
    assert abs(corr) < 0.05
    # randint covers its range
    r = prng.randint(key, 3, 10, 15)
    assert set(np.asarray(r).tolist()) == {10, 11, 12, 13, 14}


def _deterministic_gossip_spec(n_nodes=4):
    """A protocol whose handlers use NO randomness: every node broadcasts on
    a fixed-period timer and folds received (src, value) pairs into an
    order-sensitive accumulator. With fixed latency, zero loss, and no
    chaos, the ONLY seed-dependent behavior is the engine's scheduling
    (tie-break + message-vs-timer order)."""
    from madsim_tpu.tpu.spec import Outbox, ProtocolSpec

    N = n_nodes
    peers = jnp.arange(N, dtype=jnp.int32)

    from typing import NamedTuple

    class GS(NamedTuple):
        acc: jnp.ndarray
        round: jnp.ndarray

    def init(key, nid):
        return GS(acc=jnp.int32(1), round=jnp.int32(0)), jnp.int32(1_000)

    def on_message(s, nid, src, kind, payload, now, key):
        # order-sensitive fold: delivering A-then-B differs from B-then-A
        acc = s.acc * jnp.int32(31) + src * jnp.int32(7) + payload[0]
        out = Outbox(
            valid=jnp.zeros((1,), jnp.bool_),
            dst=jnp.zeros((1,), jnp.int32),
            kind=jnp.zeros((1,), jnp.int32),
            payload=jnp.zeros((1, 1), jnp.int32),
        )
        return s._replace(acc=acc), out, jnp.int32(-1)

    def on_timer(s, nid, now, key):
        # also fold the timer event itself: message-vs-timer order matters
        acc = s.acc * jnp.int32(17) + jnp.int32(5)
        out = Outbox(
            valid=peers != nid,
            dst=peers,
            kind=jnp.zeros((N,), jnp.int32),
            payload=jnp.broadcast_to(s.round[None, None], (N, 1)),
        )
        return s._replace(acc=acc, round=s.round + 1), out, now + jnp.int32(100_000)

    def on_restart(s, nid, now, key):
        return s, jnp.int32(1_000)

    def check_invariants(ns, alive, now):
        return jnp.bool_(True)

    return ProtocolSpec(
        name="gossip",
        n_nodes=N,
        payload_width=1,
        max_out=N,
        max_out_msg=1,
        init=init,
        on_message=on_message,
        on_timer=on_timer,
        on_restart=on_restart,
        check_invariants=check_invariants,
    )


def test_scheduling_order_nondeterminism_diverges():
    """Identical chaos schedules (none), fixed latency, zero loss — the only
    randomness left is delivery ordering. Seeds must still diverge (the
    utils/mpsc.rs:71-84 random-pop analog), and turning sched_randomize off
    must collapse every lane onto one identical trajectory."""
    spec = _deterministic_gossip_spec(4)
    cfg = dict(
        horizon_us=1_000_000,
        latency_lo_us=1_000,
        latency_hi_us=1_000,  # lo == hi: constant latency, no jitter
        loss_rate=0.0,
    )

    sim = BatchedSim(spec, SimConfig(**cfg, sched_randomize=True))
    state = sim.run(jnp.arange(16), max_steps=5_000)
    accs = np.asarray(state.node.acc)
    assert len({tuple(row) for row in accs.tolist()}) > 1, (
        "seeds with identical chaos schedules must diverge purely from "
        "delivery ordering"
    )

    det = BatchedSim(spec, SimConfig(**cfg, sched_randomize=False))
    dstate = det.run(jnp.arange(16), max_steps=5_000)
    daccs = np.asarray(dstate.node.acc)
    assert len({tuple(row) for row in daccs.tolist()}) == 1, (
        "with sched_randomize off and no other randomness, every lane must "
        "follow the same trajectory"
    )


def test_deposed_leader_restamp_bug_caught_on_device():
    """The interleaving bug the round-2 HOST fuzz found (commit 9229fd2): a
    deposed leader re-stamps its stale log with the newly adopted term,
    making committed prefixes disagree in term. The device fuzz must catch
    this class too."""
    spec = make_raft_spec(5, client_rate=0.8)

    def buggy_on_message(s, nid, src, kind, payload, now, key):
        state, out, timer = spec.on_message(s, nid, src, kind, payload, now, key)
        deposed = (s.role == raft_mod.LEADER) & (state.role != raft_mod.LEADER)
        log_idx = jnp.arange(s.log_term.shape[0], dtype=jnp.int32)
        in_log = log_idx < state.log_len
        log_term = jnp.where(deposed & in_log, state.term, state.log_term)
        return state._replace(log_term=log_term), out, timer

    buggy = replace_handlers(spec, on_message=buggy_on_message)
    sim = BatchedSim(buggy, partition_config(loss_rate=0.1))
    state = sim.run(jnp.arange(256), max_steps=60_000)
    s = summarize(state)
    assert s["violations"] > 0


def test_deadlock_detection():
    # a protocol with no timers and no messages deadlocks immediately
    spec = make_raft_spec(5)

    def no_timer_init(key, nid):
        state, _ = spec.init(key, nid)
        return state, jnp.int32(2**31 - 1)  # INF: no timer ever

    dead = dataclasses.replace(spec, init=no_timer_init)
    sim = BatchedSim(dead, SimConfig(horizon_us=1_000_000))
    state = sim.run(jnp.arange(4), max_steps=100)
    s = summarize(state)
    assert s["deadlocked"] == 4


def test_snapshot_ack_regression_compaction_under_partitions():
    """The fuzz-found InstallSnapshot-ack bug (round 3): a non-adopting
    follower acked match = log_len - 1, claiming its unverified (possibly
    divergent) tail as matched, so a leader could advance commit over
    entries the follower never had — split-brain commits. 8/512 lanes
    violated under the first config that combined compaction pressure
    (client_rate 0.5), partitions AND crashes; the C++ baseline fuzzer
    (native/raft_bench.cpp) found it independently. The fixed ack claims
    only the committed intersection. This config is the regression net."""
    sim = BatchedSim(
        make_raft_spec(5, client_rate=0.5),
        SimConfig(
            horizon_us=10_000_000,
            loss_rate=0.1,
            crash_interval_lo_us=500_000,
            crash_interval_hi_us=3_000_000,
            restart_delay_lo_us=300_000,
            restart_delay_hi_us=2_000_000,
            partition_interval_lo_us=300_000,
            partition_interval_hi_us=1_500_000,
            partition_heal_lo_us=500_000,
            partition_heal_hi_us=2_000_000,
        ),
    )
    # violating lanes under the old ack included 0 and 9 (seeds 0-255)
    state = sim.run(jnp.arange(256), max_steps=80_000)
    s = summarize(state, sim.spec)
    assert s["violations"] == 0
    # compaction really ran under this chaos (the bug's precondition)
    assert float(np.asarray(state.node.base).mean()) > 10
    # and no SNAP-loop wedge (review-found liveness hole in the first ack
    # fix): laggards keep catching up, so per-lane commit spread stays at
    # partition-lag scale instead of growing with the horizon
    commit = np.asarray(state.node.commit)
    spread = commit.max(axis=1) - commit.min(axis=1)
    assert np.percentile(spread, 90) < 60, spread


def test_chain_cache_coherence():
    """The incremental chain-hash cache (log_chain) must be bit-exact with
    a from-scratch recompute — the invariant check trusts it. This config
    exercises every maintenance path: appends, conflict overwrites,
    compaction shifts, InstallSnapshot clears, crash restarts."""
    sim = BatchedSim(
        make_raft_spec(5, client_rate=0.5),
        SimConfig(
            horizon_us=6_000_000,
            loss_rate=0.1,
            crash_interval_lo_us=500_000,
            crash_interval_hi_us=2_000_000,
            restart_delay_lo_us=300_000,
            restart_delay_hi_us=1_500_000,
            partition_interval_lo_us=300_000,
            partition_interval_hi_us=1_500_000,
            partition_heal_lo_us=500_000,
            partition_heal_hi_us=2_000_000,
        ),
    )
    state = sim.run(jnp.arange(128), max_steps=50_000)
    s = summarize(state, sim.spec)
    assert s["violations"] == 0
    assert float(np.asarray(state.node.base).mean()) > 10  # compaction ran
    assert raft_mod.verify_chain_cache(state.node)


def test_lookahead_window_batches_independent_events():
    """The conservative-DES lookahead (SimConfig.lookahead) must (a) raise
    events per step vs the single-instant mode, (b) keep every event inside
    the causal window [t_next, t_next + latency_lo), verified per node from
    a traced run: each node's event times are non-decreasing, and no two
    same-step events on different nodes are ever closer than a message could
    travel (they are causally independent by the latency_lo bound)."""
    mk = lambda look: BatchedSim(
        make_raft_spec(5, client_rate=0.3),
        SimConfig(
            horizon_us=3_000_000,
            loss_rate=0.1,
            lookahead=look,
            crash_interval_lo_us=400_000,
            crash_interval_hi_us=1_500_000,
            restart_delay_lo_us=200_000,
            restart_delay_hi_us=800_000,
        ),
    )
    ev_per_step = {}
    for look in (False, True):
        sim = mk(look)
        state = sim.run(jnp.arange(96), max_steps=30_000)
        s = summarize(state, sim.spec)
        assert s["violations"] == 0
        ev_per_step[look] = s["total_events"] / np.asarray(state.steps).sum()
    assert ev_per_step[True] > ev_per_step[False] * 1.05, ev_per_step

    # traced single lane: per-node event-time monotonicity + window bound
    sim = mk(True)
    _, recs = sim.run_traced(7, max_steps=4_000)
    t_evt = np.asarray(recs.t_evt)[:, 0]  # [T,N]
    fired = np.asarray(recs.msg_fired)[:, 0] | np.asarray(recs.timer_fired)[:, 0]
    lo = sim.config.latency_lo_us
    last = np.full(t_evt.shape[1], -1)
    for t in range(t_evt.shape[0]):
        if not fired[t].any():
            continue
        w_start = t_evt[t].min()  # inactive nodes default to t_next
        ts = t_evt[t][fired[t]]
        assert (ts < w_start + lo).all(), (t, w_start, ts)  # causal window
        for n in np.nonzero(fired[t])[0]:
            assert t_evt[t, n] >= last[n], (t, n)  # per-node order exact
            last[n] = t_evt[t, n]


def test_leader_completeness_invariant_crafted_states():
    """Unit cases for the Leader Completeness check (Raft §5.4): a bound
    leader missing a committed entry violates; a deposed lower-term leader
    and a compacted-past leader do not (the false-positive traps)."""
    spec = make_raft_spec(3, log_capacity=8)
    node, _timer = jax.vmap(spec.init, in_axes=(0, 0))(
        jnp.zeros((3,), jnp.uint32), jnp.arange(3, dtype=jnp.int32)
    )
    alive = jnp.ones((3,), jnp.bool_)
    now = jnp.int32(1_000_000)
    e_hash = raft_mod._chain_fold(jnp.uint32(0), 1, 7)  # entry (term=1, cmd=7)

    def with_entry(n, i):
        """Give node i entry (1,7) at index 0, committed."""
        return n._replace(
            log_term=n.log_term.at[i, 0].set(1),
            log_cmd=n.log_cmd.at[i, 0].set(7),
            log_chain=n.log_chain.at[i, 0].set(e_hash),
            log_len=n.log_len.at[i].set(1),
            commit=n.commit.at[i].set(0),
        )

    ok = lambda n: bool(spec.check_invariants(n, alive, now))

    # node 1 committed an entry; node 0 is a leader of term >= node 1's
    # term but holds nothing => INCOMPLETE leader, must violate
    bad = with_entry(node, 1)._replace(
        role=node.role.at[0].set(raft_mod.LEADER),
        term=node.term.at[0].set(5),
    )
    assert not ok(bad)

    # same leader, but deposed: term 5 < node 1's term 7 — it simply has
    # not heard of the new term yet; must NOT be flagged
    deposed = bad._replace(term=bad.term.at[1].set(7))
    assert ok(deposed)

    # complete leader: same entry in its log — passes
    good = with_entry(bad, 0)
    assert ok(good)

    # leader compacted PAST the committed index (snapshot covers it):
    # base=2 > commit[1]+1, retains nothing at index 0 — passes on length
    compacted = with_entry(node, 1)._replace(
        role=node.role.at[0].set(raft_mod.LEADER),
        term=node.term.at[0].set(5),
        base=node.base.at[0].set(2),
        base_hash=node.base_hash.at[0].set(12345),
        log_len=node.log_len.at[0].set(2),
        commit=node.commit.at[0].set(1),
    )
    assert ok(compacted)

    # complete in length but chain-DIVERGENT at the committed index:
    # leader holds a different entry at index 0 => must violate
    divergent = with_entry(node, 1)._replace(
        role=node.role.at[0].set(raft_mod.LEADER),
        term=node.term.at[0].set(5),
        log_term=node.log_term.at[0, 0].set(2),
        log_cmd=node.log_cmd.at[0, 0].set(99),
        log_chain=node.log_chain.at[0, 0].set(
            raft_mod._chain_fold(jnp.uint32(0), 2, 99)
        ),
        log_len=node.log_len.at[0].set(1),
    )
    assert not ok(divergent)


@pytest.mark.deep
def test_unsafe_election_bug_caught_by_leader_completeness():
    """Injected bug: voters grant votes WITHOUT the log up-to-date check
    (Raft §5.4.1's election restriction removed). Candidates behind the
    committed prefix then win elections; Leader Completeness catches the
    incomplete leader directly — before it has to actively destroy
    committed state to be noticed."""
    spec = make_raft_spec(5, client_rate=0.8)

    def unsafe_vote(s, nid, src, kind, payload, now, key):
        state, out, timer = spec.on_message(s, nid, src, kind, payload, now, key)
        is_rv = kind == raft_mod.REQUEST_VOTE
        c_term = payload[0]
        newer = c_term > s.term
        term = jnp.where(newer, c_term, s.term)
        voted_for = jnp.where(newer, -1, s.voted_for)
        # the buggy grant: no comparison of candidate log freshness
        grant = is_rv & (c_term == term) & ((voted_for == -1) | (voted_for == src))
        # overwrite the VOTE_RESP's granted field in WHICHEVER outbox row
        # carries the reply (replies alternate rows via reply_parity)
        pay = jnp.where(
            (is_rv & out.valid)[:, None]
            & (jnp.arange(out.payload.shape[1]) == 1)[None, :],
            grant.astype(jnp.int32),
            out.payload,
        )
        state = state._replace(
            voted_for=jnp.where(is_rv & grant, src, state.voted_for)
        )
        return state, out._replace(payload=pay), timer

    buggy = replace_handlers(spec, on_message=unsafe_vote)
    sim = BatchedSim(buggy, partition_config(loss_rate=0.1))
    state = sim.run(jnp.arange(256), max_steps=60_000)
    assert summarize(state)["violations"] > 0

    # control: the correct spec stays safe under the identical chaos
    sim_ok = BatchedSim(spec, partition_config(loss_rate=0.1))
    state_ok = sim_ok.run(jnp.arange(256), max_steps=60_000)
    assert summarize(state_ok)["violations"] == 0
