"""The etcd sim itself under the EXACT linearizability checker
(VERDICT r4 item 7; BASELINE config #4 end-to-end).

Multiple client nodes run txn-guarded writes and plain reads against the
etcd sim under partition chaos, recording acked ops with virtual
invoke/response times; the recorded per-key histories go through the same
Wing-Gong checker the device kv fuzz uses (tpu/linearize.py). A
deliberately-broken txn path — reports success without applying its
writes (the lost-update bug) — must be caught.
"""

from __future__ import annotations

import pytest

import madsim_tpu as ms
from madsim_tpu.net import NetSim
from madsim_tpu.sims.etcd import Client, SimServer
from madsim_tpu.sims.etcd.service import Compare, CompareOp, ServiceInner, Txn, TxnOp
from madsim_tpu.tpu.linearize import Op, check_key_history

N_CLIENTS = 3
N_KEYS = 3
RPC_TIMEOUT = 0.3


async def _client_loop(cid: int, history: list) -> None:
    client = await Client.connect(["10.0.9.1:2379"])
    kv = client.kv_client()
    t = ms.time.current()
    counter = 0
    while True:
        await ms.time.sleep(0.02 + ms.rand() * 0.05)
        key_i = ms.randrange(N_KEYS)
        key = f"k{key_i}"
        tinv = t.elapsed()
        try:
            if ms.rand() < 0.5:
                counter += 1
                val = cid * 100_000 + counter
                # txn-guarded write: the guard always holds (key != marker),
                # routing every write through the TXN path under test
                txn = Txn(
                    compare=[
                        Compare(key.encode(), CompareOp.NOT_EQUAL, b"marker")
                    ],
                    success=[TxnOp.put(key, str(val))],
                    failure=[],
                )
                resp = await ms.time.timeout(RPC_TIMEOUT, kv.txn(txn))
                if not resp.succeeded:
                    continue
                history.append(Op(
                    tinv=int(tinv * 1e6), trsp=int(t.elapsed() * 1e6),
                    is_write=True, key=key_i, val=val,
                    rev=resp.header.revision, node=cid,
                ))
            else:
                resp = await ms.time.timeout(RPC_TIMEOUT, kv.get(key))
                if resp.kvs:
                    val = int(resp.kvs[0].value)
                    rev = resp.kvs[0].mod_revision
                else:
                    val, rev = 0, 0
                history.append(Op(
                    tinv=int(tinv * 1e6), trsp=int(t.elapsed() * 1e6),
                    is_write=False, key=key_i, val=val, rev=rev, node=cid,
                ))
        except (ms.time.TimeoutError_, OSError, ms.sync.ChannelClosed):
            continue  # unacked: excluded from the recorded history


async def _fuzz(handle, virtual_secs: float) -> list:
    server = (
        handle.create_node().name("etcd").ip("10.0.9.1")
        .init(lambda: SimServer.builder().serve("10.0.9.1:2379"))
        .build()
    )
    await ms.time.sleep(0.5)
    history: list = []
    clients = []
    for cid in range(N_CLIENTS):
        node = (
            handle.create_node().name(f"cl-{cid}").ip(f"10.0.9.{cid + 2}")
            .build()
        )
        node.spawn(_client_loop(cid, history))
        clients.append(node)

    async def partition_task() -> None:
        net = ms.plugin.simulator(NetSim)
        while True:
            await ms.time.sleep(0.3 + ms.rand() * 0.9)
            # cut a random subset of clients off the server
            side = [c.id for c in clients if ms.rand() < 0.5]
            if not side:
                continue
            net.partition(side, [server.id])
            await ms.time.sleep(0.2 + ms.rand() * 0.6)
            net.heal_partition(side, [server.id])

    ms.spawn(partition_task())

    t = ms.time.current()
    end = t.elapsed() + virtual_secs
    while t.elapsed() < end:
        await ms.time.sleep(0.05)
    return history


def _check(history: list) -> dict:
    by_key: dict = {}
    for o in history:
        by_key.setdefault(o.key, []).append(o)
    failures = []
    checked = 0
    for k, ops in sorted(by_key.items()):
        ok, ce, _unmatched = check_key_history(ops)
        checked += len(ops)
        if not ok:
            failures.append((k, [str(o) for o in (ce or [])[-8:]]))
    return {"ops": checked, "failures": failures}


def _run(seed: int, virtual_secs: float = 8.0) -> dict:
    rt = ms.Runtime(seed=seed)
    history = rt.block_on(_fuzz(rt.handle, virtual_secs))
    return _check(history)


def test_etcd_linearizable_under_partitions():
    out = _run(seed=11)
    assert out["ops"] > 100, "the fuzz must actually exercise the store"
    assert not out["failures"], out["failures"]


def test_broken_txn_path_caught(monkeypatch):
    """Deliberately-broken txn: reports success but silently drops its
    write ops (the lost-update bug). The exact checker must object —
    reads keep returning values that acked txn writes should have
    replaced."""
    orig = ServiceInner.txn

    def lost_update_txn(self, txn: Txn):
        hollow = Txn(compare=txn.compare, success=[], failure=txn.failure)
        return orig(self, hollow)

    monkeypatch.setattr(ServiceInner, "txn", lost_update_txn)
    hits = 0
    for seed in (11, 12, 13):
        out = _run(seed=seed, virtual_secs=6.0)
        hits += bool(out["failures"])
    assert hits > 0, "lost txn updates must break linearizability"
