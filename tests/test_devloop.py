"""Device-resident search (r19, docs/explore.md): the explorer's
generation loop runs in-jit — ranking, mutation and admission on device,
one host sync per window — and the acceptance contract is bit-identity:
corpus contents, curves, violations and fingerprints equal the host loop
exactly, window partition and dispatch shape notwithstanding.

`chaos`-marked tests run in the explore-smoke tier; the cross-process
CLI sweep is `slow` (nightly) because each subprocess pays a cold
compile. The in-process tests run under conftest's 8 forced host
devices; the subprocess runs under the default single device, so the
two together pin device-count independence of the fingerprint.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from madsim_tpu import campaign, telemetry
from madsim_tpu.explore import (
    Candidate,
    CorpusEntry,
    Explorer,
    ExploreReport,
    Federation,
    genome_hash64,
)

from tests.test_explore import _planted_workload

LANES = 16
CHUNK = 8
SEEN_CAP = 512  # power of two; headroom for every window in the suite
META_SEED = 11
GENS = 3


@pytest.fixture(scope="module")
def planted():
    """The planted workload + ONE devloop-plan sim shared by every
    in-process test (host AND device explorers — a devloop plan is
    inert outside `init_devloop`), so the engine compiles once."""
    from madsim_tpu.tpu.engine import BatchedSim, make_devloop_plan

    wl = _planted_workload()
    plan = make_devloop_plan(
        wl.config, pop=LANES, top_k=16, seen_cap=SEEN_CAP
    )
    sim = BatchedSim(
        wl.spec, wl.config, triage=True, coverage=True, devloop=plan
    )
    return wl, sim


def _explorer(wl, sim, **kw):
    base = dict(
        meta_seed=META_SEED, lanes=LANES, chunk=CHUNK,
        shrink_violations=False, seen_cap=SEEN_CAP, sim=sim,
    )
    base.update(kw)
    return Explorer(wl, **base)


@pytest.fixture(scope="module")
def host_baseline(planted):
    """The host-loop reference run every device variant must match, plus
    its dispatch cost (deltas on the shared sim's counter)."""
    wl, sim = planted
    d0 = sim.dispatch_count
    rep = _explorer(wl, sim).run(GENS)
    return rep, sim.dispatch_count - d0


def _count_syncs(monkeypatch):
    """Count the device loop's host syncs (devloop_results decodes) —
    the budget the tentpole buys down to one per window."""
    from madsim_tpu.tpu import engine

    calls = []
    real = engine.devloop_results
    monkeypatch.setattr(
        engine, "devloop_results",
        lambda st: calls.append(1) or real(st),
    )
    return calls


def _assert_bit_identical(dev: ExploreReport, host: ExploreReport):
    assert dev.fingerprint() == host.fingerprint()
    assert dev.coverage_curve == host.coverage_curve
    assert dev.corpus_curve == host.corpus_curve
    assert dev.violation_curve == host.violation_curve
    assert dev.corpus_digest == host.corpus_digest
    assert dev.violations == host.violations
    assert dev.seeds_run == host.seeds_run


# ----------------------------------------------------- host/device identity


@pytest.mark.chaos
def test_device_loop_matches_host_loop_bit_for_bit(
    planted, host_baseline, monkeypatch
):
    """The tentpole contract: device_window=2 over 3 generations (a full
    window then a partial one) produces the host loop's exact corpus,
    curves and fingerprint — in strictly fewer dispatches, with ONE host
    sync per window."""
    wl, sim = planted
    host_rep, host_d = host_baseline
    syncs = _count_syncs(monkeypatch)
    d0 = sim.dispatch_count
    dev_rep = _explorer(
        wl, sim, device_loop=True, device_window=2
    ).run(GENS)
    dev_d = sim.dispatch_count - d0
    _assert_bit_identical(dev_rep, host_rep)
    assert dev_d < host_d
    assert len(syncs) == 2  # windows 2+1: one decode each, <= 1/gen


@pytest.mark.chaos
def test_device_loop_single_window_covers_all_generations(
    planted, host_baseline, monkeypatch
):
    """All 3 generations inside ONE device window: the deepest in-jit
    chain still lands bit-identical, with a SINGLE host sync for the
    whole search."""
    wl, sim = planted
    host_rep, _ = host_baseline
    syncs = _count_syncs(monkeypatch)
    dev_rep = _explorer(
        wl, sim, device_loop=True, device_window=GENS
    ).run(GENS)
    _assert_bit_identical(dev_rep, host_rep)
    assert len(syncs) == 1  # three generations, one decode


@pytest.mark.chaos
def test_device_loop_pipeline_flag_is_identity(planted, host_baseline):
    """`pipeline` is a dispatch-shape knob outside the search identity;
    the device loop must keep that true (it shares run_state with every
    other mode)."""
    wl, sim = planted
    host_rep, _ = host_baseline
    dev_rep = _explorer(
        wl, sim, device_loop=True, device_window=2, pipeline=False
    ).run(GENS)
    assert dev_rep.fingerprint() == host_rep.fingerprint()


# --------------------------------------------------------- kill / resume


@pytest.mark.chaos
def test_campaign_kill_resume_mid_ring(tmp_path, planted):
    """Kill/resume bit-identity THROUGH the device loop: checkpoint at
    generation 1 (the corpus ring is live, mid-window-schedule), resume
    into a fresh Campaign, run 2 more — fingerprint equals the
    uninterrupted 3-generation device-loop run even though the window
    partition differs (2+1 uninterrupted vs 1 then 2 resumed). The
    resume reconstructs device_loop/device_window/seen_cap from the
    persisted explorer_params."""
    wl, sim = planted
    kw = dict(
        meta_seed=META_SEED, lanes=LANES, chunk=CHUNK, shrink=False,
        sim=sim, explorer_kwargs=dict(
            device_loop=True, device_window=2, seen_cap=SEEN_CAP,
        ),
    )
    full = campaign.Campaign(wl, str(tmp_path / "full"), **kw)
    rep_full = full.run(GENS)

    part = campaign.Campaign(wl, str(tmp_path / "part"), **kw)
    part.run(1)
    part.checkpoint()
    del part  # the "kill": only the checkpoint survives

    resumed = campaign.Campaign.resume(
        str(tmp_path / "part"), workload=wl, sim=sim
    )
    assert resumed.generation == 1
    assert resumed.ex.device_loop
    assert resumed.ex.device_window == 2
    rep_res = resumed.run(GENS - 1)

    _assert_bit_identical(rep_res, rep_full)


# ------------------------------------------------------------- federation


@pytest.mark.chaos
def test_federation_device_loop_matches_host_federation():
    """Island federation with device-resident islands: windows clip to
    exchange boundaries, and the federation fingerprint AND exchange log
    equal the host-loop federation exactly — which is what keeps the
    fingerprint pinned across device counts (the host-loop federation's
    own invariance is pinned in test_multichip)."""
    from madsim_tpu.tpu.engine import BatchedSim, make_devloop_plan

    wl = _planted_workload()
    # island fresh sub-queues: first_seed=i, stride=n_islands — the plan
    # must carry the federation's stride
    plan = make_devloop_plan(
        wl.config, pop=8, top_k=16, seen_cap=SEEN_CAP, fresh_stride=2
    )
    sim = BatchedSim(
        wl.spec, wl.config, triage=True, coverage=True, devloop=plan
    )
    kw = dict(
        n_islands=2, meta_seed=7, lanes=8, exchange_every=2,
        mesh=None, sim=sim, seen_cap=SEEN_CAP,
    )
    host = Federation(wl, **kw).run(4)
    # device_window=3 > exchange_every forces the clip
    dev = Federation(
        wl, device_loop=True, device_window=3, **kw
    ).run(4)
    assert dev["fingerprint"] == host["fingerprint"]
    assert dev["exchanges"] == host["exchanges"]
    assert dev["coverage_bits"] == host["coverage_bits"]
    assert dev["violations"] == host["violations"]


# ------------------------------------------- counter alignment (mutation)


def _plant_parents(ex, n=3):
    """Synthesize corpus parents with novelty (no device work): ranking
    only reads (new_bits, dispatch, cand)."""
    from madsim_tpu.tpu.engine import COV_WORDS

    for i in range(n):
        bm = np.zeros((COV_WORDS,), np.uint32)
        bm[i] = 1
        cand = Candidate(seed=10_000 + i)
        ex._claim(cand)
        ex.corpus.append(CorpusEntry(
            cand=cand, new_bits=n - i, bitmap=bm, hiwater=0,
            transitions=0, violated=False, dispatch=0,
        ))


@pytest.mark.chaos
def test_population_counter_alignment_and_draw_free_fallback(planted):
    """The satellite-1 pin: a mutant slot is ONE fixed draw schedule
    (parent + op + params, 3 or 4 meta draws by op — the device's
    adv_of table), and a seen-duplicate falls back to the next fresh
    seed WITHOUT consuming any draw. Host-only: no dispatch."""
    from madsim_tpu.nemesis import mutation_vocab

    wl, sim = planted
    a = _explorer(wl, sim)
    _plant_parents(a)
    c0, s0 = a._rng.counter, len(a._seen_h)
    pop_a = a._population(1)
    delta_a = a._rng.counter - c0

    assert len(pop_a) == LANES
    # the population layout is plan arithmetic (the device mirrors it):
    # fresh block, then the mutant slots, then swarm groups
    n_mut = int(LANES * a.mutant_frac)
    n_fresh0 = int(LANES * a.fresh_frac)
    n_swarm = LANES - n_mut - n_fresh0 if a._togglable else 0
    n_fresh = LANES - n_mut - n_swarm
    mslots = range(n_fresh, n_fresh + n_mut)
    # a mutant slot is origin "mutant", or "fresh" when its drawn genome
    # was already claimed (the draw-free fallback)
    assert all(pop_a[i].origin in ("mutant", "fresh") for i in mslots)
    assert all(pop_a[i].origin == "fresh" for i in range(n_fresh))
    # exactly ONE new genome claimed per slot — the host seen-set and
    # the device seen-table grow in lockstep
    assert len(a._seen_h) - s0 == LANES
    # the advance table's bounds: 3..4 draws per mutant (parent + op +
    # params, whether or not it falls back), plus one coin per togglable
    # clause per swarm group
    sched, rate, togglable = mutation_vocab(a.cfg)
    n_groups = (n_swarm + a.swarm_group - 1) // a.swarm_group
    swarm_draws = n_groups * len(togglable)
    assert 3 * n_mut + swarm_draws <= delta_a <= 4 * n_mut + swarm_draws

    # now the SAME search, but one surviving mutant's genome is
    # pre-claimed: the slot must fall back fresh with an IDENTICAL
    # counter advance (the fallback consumes no draws)
    target = next(i for i in mslots if pop_a[i].origin == "mutant")
    b = _explorer(wl, sim)
    _plant_parents(b)
    b._seen_h.add(genome_hash64(pop_a[target].key()))
    c0 = b._rng.counter
    pop_b = b._population(1)
    assert b._rng.counter - c0 == delta_a  # draw-free fallback
    assert pop_b[target].origin == "fresh"  # the device's org code 0
    assert pop_b[target].off == 0 and pop_b[target].horizon_us == 0
    # every other surviving mutant slot drew the same schedule
    for i in mslots:
        if i != target and pop_a[i].origin == "mutant":
            assert pop_b[i] == pop_a[i]


# --------------------------------------------------------------- telemetry


@pytest.mark.chaos
def test_telemetry_devloop_is_observe_only(tmp_path, planted, host_baseline):
    """The satellite-6 pin: record_explore_devloop observes the window's
    decoded values at the one host sync — gauges move, the fingerprint
    (the golden) does not."""
    wl, sim = planted
    host_rep, _ = host_baseline
    telemetry.enable(out_dir=str(tmp_path))
    try:
        dev_rep = _explorer(
            wl, sim, device_loop=True, device_window=2
        ).run(GENS)
        reg = telemetry.get_registry()
        total = reg.counter(
            "explore_devloop_generations"
        ).value(meta_seed=META_SEED)
        assert total == GENS
        # last window of the 2+1 partition retired one generation
        assert reg.gauge(
            "explore_devloop_window_generations"
        ).value(meta_seed=META_SEED) == 1
        occ = reg.gauge(
            "explore_devloop_ring_occupancy"
        ).value(meta_seed=META_SEED)
        assert 0.0 <= occ <= 1.0
        # one genome claimed per lane per generation, both faces
        assert reg.gauge(
            "explore_devloop_seen_rows"
        ).value(meta_seed=META_SEED) == GENS * LANES
    finally:
        telemetry.disable()
    assert dev_rep.fingerprint() == host_rep.fingerprint()
    events = telemetry.read_events(str(tmp_path / "events.jsonl"))
    assert any(
        e["name"] == "explore_devloop_ring_occupancy" for e in events
    )


# ---------------------------------------------------------------- the CLI


@pytest.mark.chaos
def test_cli_device_loop_in_process(planted, host_baseline, monkeypatch,
                                    capsys):
    """`--device-loop --device-window` through main(): the JSON report
    fingerprints identically to the host baseline."""
    from madsim_tpu import explore

    wl, sim = planted
    host_rep, _ = host_baseline
    monkeypatch.setattr(explore, "_named_workload", lambda *a: wl)
    orig_init = Explorer.__init__
    monkeypatch.setattr(
        Explorer, "__init__",
        lambda self, *a, **k: orig_init(self, *a, **{**k, "sim": sim}),
    )
    explore.main([
        "--workload", "raft", "--meta-seed", str(META_SEED),
        "--lanes", str(LANES), "--chunk", str(CHUNK),
        "--dispatches", str(GENS), "--no-shrink",
        "--device-loop", "--device-window", "2", "--json",
    ])
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rep = ExploreReport.from_json(line)
    assert rep.fingerprint() == host_rep.fingerprint()


@pytest.mark.slow
def test_cli_device_loop_cross_process_bit_identity(tmp_path):
    """Two COLD processes — default device topology (one host device,
    unlike conftest's forced 8), zero shared state — agree bit-for-bit
    across the host/device loop boundary."""
    base = [
        sys.executable, "-m", "madsim_tpu.explore",
        "--workload", "raft", "--virtual-secs", "0.5",
        "--meta-seed", "3", "--lanes", "8", "--chunk", "8",
        "--dispatches", "3", "--no-shrink", "--json",
    ]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)

    def run(extra):
        out = subprocess.run(
            base + extra, env=env, capture_output=True, text=True,
            timeout=900, check=True,
        )
        return ExploreReport.from_json(out.stdout.strip().splitlines()[-1])

    host = run([])
    dev = run(["--device-loop", "--device-window", "2"])
    assert dev.fingerprint() == host.fingerprint()
    assert dev.coverage_curve == host.coverage_curve
    assert dev.device_dispatches < host.device_dispatches
