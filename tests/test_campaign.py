"""Campaign mode: persistent corpus, coverage-signature bug dedup, and the
fuzz-service front end (madsim_tpu/campaign).

The subsystem's contract (docs/campaign.md):
  * kill/resume bit-identity: a campaign checkpointed at generation k and
    resumed for k' more produces the SAME `ExploreReport.fingerprint()` as
    the uninterrupted k+k' run — in-process and cross-process;
  * corpus merge + cmin minimization provably preserve the coverage union
    (popcount AND exact array equality, asserted in campaign.py itself);
  * bug dedup collapses a seed-dense planted bug to exactly one BugRecord
    with N witness seeds, whose shrunk bundle replays green from the
    regression corpus.

`chaos`-marked tests are the campaign-smoke tier (`make campaign-smoke`);
`slow`-marked cross-process/e2e runs go nightly.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from madsim_tpu import campaign
from madsim_tpu.explore import (
    Candidate,
    CorpusEntry,
    Explorer,
    ExploreReport,
    canon_genome,
)

from tests.test_explore import _planted_workload

REPO = str(Path(__file__).resolve().parent.parent)


@pytest.fixture(scope="module")
def planted():
    """One compiled (triage+coverage) sim shared by every device test in
    this module — search, resume, dedup-shrink and cmin replay all reuse
    it (the lane_width=16 shrink programs compile separately, once)."""
    from madsim_tpu.tpu.engine import BatchedSim

    wl = _planted_workload()
    sim = BatchedSim(wl.spec, wl.config, triage=True, coverage=True)
    return wl, sim


def _report(meta_seed=1, violations=()):
    return ExploreReport(
        meta_seed=meta_seed, lanes=4, dispatches=1, coverage_curve=[3],
        corpus_curve=[1], violation_curve=[len(violations)],
        violations=list(violations), coverage_bits=3, corpus_size=1,
        seeds_run=4, first_violation_dispatch=None, wall_s=0.1,
        device_dispatches=2, corpus_digest="00" * 32,
    )


# ------------------------------------------------------------- pure pieces


def test_bug_signature_keys_on_minimal_plan_shape():
    """The dedup key: clause profile of the shrunk plan — occurrence
    INDICES excluded (seed-local), counts and whole-clause atoms kept."""
    sig = campaign.bug_signature
    # which crash window triggered it varies seed to seed; the shape
    # "one partition occurrence + one crash occurrence" is the class
    assert sig("raft", "invariant", [("partition", 3), ("crash", 1)]) == \
        sig("raft", "invariant", [("crash", 7), ("partition", 0)])
    assert sig("raft", "invariant", [("partition", 0)]) != \
        sig("raft", "invariant", [("partition", 0), ("partition", 1)])
    assert sig("raft", "invariant", []) != sig("kv", "invariant", [])
    assert sig("raft", "invariant", [("loss", None)]) != \
        sig("raft", "invariant", [("loss", 0)])
    assert campaign.clause_profile(
        [("crash", 2), ("crash", 5), ("loss", None)]
    ) == [["crash", 2], ["loss", -1]]
    # the coarse (pre-shrink) grouping key ignores the SEED, keeps the ctl
    g1 = (3, 1, (0, 2, 0, 0), (1.0, 1.0, 1.0), 0)
    g2 = (99, 1, (0, 2, 0, 0), (1.0, 1.0, 1.0), 0)
    g3 = (3, 0, (0, 2, 0, 0), (1.0, 1.0, 1.0), 0)
    assert campaign.coarse_key("raft", "invariant", g1) == \
        campaign.coarse_key("raft", "invariant", g2)
    assert campaign.coarse_key("raft", "invariant", g1) != \
        campaign.coarse_key("raft", "invariant", g3)


def test_bugrecord_roundtrip():
    rec = campaign.BugRecord(
        signature="s1", spec_name="raft", violation_kind="invariant",
        clause_profile=[["partition", 1]],
        witnesses=[{"seed": 3, "candidate": [3, 0, [0] * 4, [1.0] * 3, 0],
                    "dispatch": 0, "origin": "fresh", "cov_digest": "ab"}],
        bundle_path="/tmp/b.json", campaign="c1", first_generation=0,
        coarse_keys=["coarse-xyz"],
    )
    again = campaign.BugRecord.from_dict(
        json.loads(json.dumps(rec.to_dict()))
    )
    assert again == rec
    assert again.witness_seeds == [3]
    with pytest.raises(ValueError, match="unknown"):
        campaign.BugRecord.from_dict({**rec.to_dict(), "bogus": 1})


def test_checkpoint_roundtrip_pure(tmp_path):
    """save_checkpoint/load_checkpoint are exact inverses on the snapshot
    dict (manifest + jsonl split reassembles), with atomic writes."""
    bitmap = np.arange(256, dtype=np.uint32)
    snapshot = {
        "meta_seed": 7, "lanes": 16, "meta_cursor": 42, "next_fresh": 33,
        "generation": 2, "shrinks_done": 1, "seeds_run": 32,
        "first_violation_dispatch": 1, "wall_s": 1.5,
        "union": bitmap.tobytes().hex(),
        "coverage_curve": [10, 20], "corpus_curve": [1, 2],
        "violation_curve": [0, 1],
        "corpus": [CorpusEntry(
            cand=Candidate(seed=5, origin="swarm"), new_bits=10,
            bitmap=bitmap, hiwater=3, transitions=9, violated=False,
            dispatch=1,
        ).to_dict()],
        "seen": [[5, 0, [0, 0, 0, 0], [1.0, 1.0, 1.0], 0]],
        "violated_seeds": [9],
        "violations": [{"candidate": [9, 0, [0] * 4, [1.0] * 3, 0],
                        "seed": 9, "dispatch": 1, "origin": "fresh",
                        "describe": "seed=9", "bundle_path": None,
                        "cov_digest": None}],
    }
    bugs = [campaign.BugRecord(
        signature="s", spec_name="raft", violation_kind="invariant",
        clause_profile=[], witnesses=[], bundle_path=None, campaign="c",
        first_generation=1, coarse_keys=["k"],
    )]
    extra = {
        "campaign_id": "c", "workload": {"kind": "custom"},
        "config_hash": "h", "spec_name": "raft", "params": {"lanes": 16},
        "seen_violations": 1, "kind": "campaign",
    }
    d = str(tmp_path / "ck")
    campaign.save_checkpoint(d, snapshot, extra, bugs=bugs)
    back = campaign.load_checkpoint(d)
    assert back["manifest"]["campaign_id"] == "c"
    assert back["manifest"]["state"]["meta_cursor"] == 42
    assert json.loads(json.dumps(snapshot)) == back["snapshot"]
    assert back["bugs"] == bugs
    # no .tmp litter (atomic writes)
    assert not [p for p in os.listdir(d) if ".tmp" in p]
    # the manifest is the COMMIT POINT: sidecars are stamped with the
    # generation PLUS a content digest (a re-checkpoint with different
    # content never rewrites a committed manifest's files), and a new
    # checkpoint garbage-collects stale files only after its manifest lands
    import glob as globmod

    man = json.load(open(os.path.join(d, campaign.MANIFEST)))
    assert man["files"]["corpus"].startswith("corpus.2-")
    snap3 = {**snapshot, "generation": 3}
    campaign.save_checkpoint(d, snap3, extra, bugs=bugs)
    assert not globmod.glob(os.path.join(d, "corpus.2-*"))
    man3 = campaign.load_checkpoint(d)["manifest"]
    assert man3["files"]["corpus"].startswith("corpus.3-")
    # same generation, same content: identical names, still loadable;
    # different content (a bug absorbed, no new generation): FRESH names,
    # so a kill mid-save can never invalidate the committed manifest
    campaign.save_checkpoint(d, snap3, extra, bugs=bugs)
    assert campaign.load_checkpoint(d)["manifest"]["files"] == man3["files"]
    campaign.save_checkpoint(d, snap3, extra, bugs=[])
    man3b = campaign.load_checkpoint(d)["manifest"]
    assert man3b["files"]["bugs"] != man3["files"]["bugs"]
    # a torn checkpoint (sidecar not matching the manifest digest) fails
    # LOUDLY — resuming it would silently fork the search
    with open(os.path.join(d, man3b["files"]["seen"]), "a") as f:
        f.write('{"genome": [1, 0, [0,0,0,0], [1.0,1.0,1.0], 0]}\n')
    with pytest.raises(AssertionError, match="digest"):
        campaign.load_checkpoint(d)
    campaign.save_checkpoint(d, snap3, extra, bugs=bugs)  # heal
    # and a bad format marker is refused
    man = json.load(open(os.path.join(d, campaign.MANIFEST)))
    man["format"] = "bogus/9"
    json.dump(man, open(os.path.join(d, campaign.MANIFEST), "w"))
    with pytest.raises(ValueError, match="format"):
        campaign.load_checkpoint(d)


def test_serve_queue_mechanics_with_stub_campaigns(tmp_path):
    """The watch-dir protocol without a device: requests move queue/ ->
    active/ -> done/, slices round-robin, one JSON line streams per slice,
    checkpoints land between slices."""
    d = str(tmp_path / "svc")
    os.makedirs(os.path.join(d, "queue"))
    events = []

    class Stub:
        def __init__(self, cid):
            self.cid, self.generation, self.bugs = cid, 0, []

        def run(self, g):
            self.generation += g
            events.append(("run", self.cid, self.generation))
            return _report()

        def checkpoint(self):
            events.append(("ckpt", self.cid, self.generation))
            os.makedirs(os.path.join(d, "campaigns", self.cid), exist_ok=True)

    def factory(request, campaign_dir, regression_dir, log):
        return Stub(request["id"])

    for name, gens in (("a", 2), ("b", 1)):
        with open(os.path.join(d, "queue", f"{name}.json"), "w") as f:
            json.dump({"workload": "raft", "generations": gens}, f)
    lines = []
    res = campaign.serve(
        d, slice_generations=1, max_rounds=5, idle_rounds=1,
        out=lambda s: lines.append(json.loads(s)), factory=factory,
        sleep=lambda s: None,
    )
    assert res["completed"] == ["b", "a"] and not res["pending"]
    # round-robin: a and b interleave, b (1 gen) finishes first
    assert [e for e in events if e[0] == "run"] == [
        ("run", "a", 1), ("run", "b", 1), ("run", "a", 2),
    ]
    # every slice checkpointed BEFORE its report line streamed
    assert events == [
        ("run", "a", 1), ("ckpt", "a", 1), ("run", "b", 1),
        ("ckpt", "b", 1), ("run", "a", 2), ("ckpt", "a", 2),
    ]
    slices = [l for l in lines if "report" in l]
    assert [(l["campaign"], l["generation"]) for l in slices] == [
        ("a", 1), ("b", 1), ("a", 2),
    ]
    assert all("fingerprint" in l for l in slices)
    for name in ("a", "b"):
        assert os.path.exists(os.path.join(d, "done", f"{name}.json"))
        assert not os.path.exists(os.path.join(d, "queue", f"{name}.json"))
        stream = campaign._read_jsonl(
            os.path.join(d, "campaigns", name, campaign.REPORTS_STREAM)
        )
        assert [s["generation"] for s in stream] == (
            [1, 2] if name == "a" else [1]
        )


def test_serve_survives_bad_requests(tmp_path):
    """One tenant must never take the service down: malformed JSON is
    retried then rejected to done/, non-positive generations and factory
    failures are rejected immediately, and a campaign whose slice raises
    is evicted while the other campaigns keep running."""
    d = str(tmp_path / "svc")
    os.makedirs(os.path.join(d, "queue"))

    class Stub:
        def __init__(self, cid, explode=False):
            self.cid, self.generation, self.explode = cid, 0, explode
            self.bugs = []

        def run(self, g):
            if self.explode:
                raise RuntimeError("planted slice failure")
            self.generation += g
            return _report()

        def checkpoint(self):
            os.makedirs(os.path.join(d, "campaigns", self.cid), exist_ok=True)

    def factory(request, campaign_dir, regression_dir, log):
        if request["id"] == "unbuildable":
            raise ValueError("unknown workload")
        return Stub(request["id"], explode=request["id"] == "explodes")

    reqs = {
        "ok": {"workload": "raft", "generations": 1},
        "explodes": {"workload": "raft", "generations": 2},
        "unbuildable": {"workload": "nope", "generations": 1},
        "zero": {"workload": "raft", "generations": 0},
    }
    for name, req in reqs.items():
        with open(os.path.join(d, "queue", f"{name}.json"), "w") as f:
            json.dump(req, f)
    with open(os.path.join(d, "queue", "garbage.json"), "w") as f:
        f.write("{not json")
    lines = []
    res = campaign.serve(
        d, slice_generations=1, max_rounds=6, idle_rounds=2,
        out=lambda s: lines.append(json.loads(s)), factory=factory,
        sleep=lambda s: None,
    )
    assert res["completed"] == ["ok"] and not res["pending"]
    rejected = {l["campaign"]: l["rejected"] for l in lines if "rejected" in l}
    assert "generations" in rejected["zero"]
    assert "unknown workload" in rejected["unbuildable"]
    assert "planted slice failure" in rejected["explodes"]
    assert any("unreadable request" in v for v in rejected.values())
    # every request file ended up in done/, none left in queue/ or active/
    for sub, want in (("queue", 0), ("active", 0), ("done", 5)):
        assert len(os.listdir(os.path.join(d, sub))) == want, sub
    # the good campaign still ran to completion
    assert [(l["campaign"], l["generation"]) for l in lines
            if "report" in l] == [("ok", 1)]


def test_serve_crash_recovery_and_total_generation_semantics(tmp_path):
    """A service restart requeues requests orphaned in active/, and
    `generations` is the campaign's TOTAL target: a resumed campaign runs
    only the remainder, an already-satisfied request completes without
    running at all."""
    d = str(tmp_path / "svc")
    os.makedirs(os.path.join(d, "queue"))
    os.makedirs(os.path.join(d, "active"))
    runs = []

    class Stub:
        def __init__(self, cid, start_gen):
            self.cid, self.generation, self.bugs = cid, start_gen, []

        def run(self, g):
            self.generation += g
            runs.append((self.cid, self.generation))
            return _report()

        def checkpoint(self):
            os.makedirs(os.path.join(d, "campaigns", self.cid), exist_ok=True)

    start_gens = {"orphan": 3, "satisfied": 5}

    def factory(request, campaign_dir, regression_dir, log):
        return Stub(request["id"], start_gens[request["id"]])

    # orphaned mid-flight by a killed service: checkpoint says gen 3 of 4
    with open(os.path.join(d, "active", "orphan.json"), "w") as f:
        json.dump({"workload": "raft", "generations": 4}, f)
    # already past its total target
    with open(os.path.join(d, "queue", "satisfied.json"), "w") as f:
        json.dump({"workload": "raft", "generations": 2}, f)
    lines = []
    res = campaign.serve(
        d, slice_generations=2, max_rounds=4, idle_rounds=1,
        out=lambda s: lines.append(json.loads(s)), factory=factory,
        sleep=lambda s: None,
    )
    assert sorted(res["completed"]) == ["orphan", "satisfied"]
    # the orphan ran exactly its REMAINDER (1 gen, though the slice is 2)
    assert runs == [("orphan", 4)]
    assert any(
        l.get("completed") and l["campaign"] == "satisfied"
        and l["generation"] == 5 for l in lines
    )
    for name in ("orphan", "satisfied"):
        assert os.path.exists(os.path.join(d, "done", f"{name}.json"))
    assert not os.listdir(os.path.join(d, "active"))


def test_serve_active_files_keyed_by_campaign_id(tmp_path):
    """In-flight requests are parked as active/<campaign id>.json: a new
    request REUSING a previous request's filename (but a distinct explicit
    id) must not clobber the in-flight file of the first."""
    d = str(tmp_path / "svc")
    os.makedirs(os.path.join(d, "queue"))

    class Stub:
        def __init__(self, cid):
            self.cid, self.generation, self.bugs = cid, 0, []

        def run(self, g):
            self.generation += g
            return _report()

        def checkpoint(self):
            os.makedirs(os.path.join(d, "campaigns", self.cid), exist_ok=True)

    def factory(request, campaign_dir, regression_dir, log):
        return Stub(request["id"])

    with open(os.path.join(d, "queue", "job.json"), "w") as f:
        json.dump({"id": "a", "workload": "raft", "generations": 2}, f)
    campaign.serve(
        d, slice_generations=1, max_rounds=1, out=lambda s: None,
        factory=factory, sleep=lambda s: None,
    )
    assert os.listdir(os.path.join(d, "active")) == ["a.json"]
    # tenant B reuses the FILENAME while a is still in flight (service
    # restarted: the orphan requeues under its id, so no name collision)
    with open(os.path.join(d, "queue", "job.json"), "w") as f:
        json.dump({"id": "b", "workload": "raft", "generations": 1}, f)
    res = campaign.serve(
        d, slice_generations=1, max_rounds=4, idle_rounds=1,
        out=lambda s: None, factory=factory, sleep=lambda s: None,
    )
    assert sorted(res["completed"]) == ["a", "b"]
    assert sorted(os.listdir(os.path.join(d, "done"))) == [
        "a.json", "b.json",
    ]
    assert not os.listdir(os.path.join(d, "active"))


def test_resume_conflict_check_only_covers_explicit_knobs():
    """Resuming under explicitly different search parameters is refused;
    omitted knobs (and chunk 0/null, the 'default' spelling) defer to the
    checkpoint — so a service restart never rejects its own request."""
    man = {
        "params": {"meta_seed": 0, "lanes": 256, "chunk": 256},
        "workload": {"kind": "named", "name": "raft",
                     "virtual_secs": 2.0, "storm": True},
    }
    campaign.check_resume_conflicts(man, {})  # nothing explicit
    campaign.check_resume_conflicts(
        man, {"workload": "raft", "virtual_secs": 2.0, "meta_seed": 0,
              "lanes": 256, "storm": True},
    )
    for given, what in (
        ({"meta_seed": 5}, "meta_seed"),
        ({"lanes": 64}, "lanes"),
        ({"chunk": 8}, "chunk"),
        ({"workload": "kv"}, "workload"),
        ({"virtual_secs": 1.0}, "virtual_secs"),
        ({"storm": False}, "storm"),
    ):
        with pytest.raises(ValueError, match=what):
            campaign.check_resume_conflicts(man, given)
    # the exact restart regression: a request that said chunk 0 ('use the
    # default') must not be treated as pinning chunk=0
    req = {"workload": "raft", "virtual_secs": 2.0, "chunk": 0,
           "meta_seed": 0, "lanes": 256, "storm": True, "generations": 4}
    given = campaign._explicit_request_params(req)
    assert "chunk" not in given
    campaign.check_resume_conflicts(man, given)
    assert campaign._explicit_request_params({"chunk": 8})["chunk"] == 8


def test_build_workload_raises_catchable_errors():
    """build_workload is a LIBRARY call: an unknown workload name must be
    a ValueError (the serve loop's per-request guard catches Exception),
    not the SystemExit the explore CLI speaks — a SystemExit would kill
    the whole multi-tenant service."""
    with pytest.raises(ValueError, match="nosuch"):
        campaign.build_workload(
            {"kind": "named", "name": "nosuch", "virtual_secs": 1.0}
        )
    with pytest.raises(ValueError, match="custom"):
        campaign.build_workload({"kind": "custom"})


def test_regress_empty_dir_is_vacuously_green(tmp_path):
    out = []
    rep = campaign.regress(str(tmp_path / "nothing"), out=out.append)
    assert rep["bundles"] == 0 and not rep["failures"]
    assert "0/0" in out[-1]


# ----------------------------------------------------------- device tests


@pytest.mark.chaos
def test_campaign_kill_resume_bit_identity_in_process(tmp_path, planted):
    """The acceptance contract: checkpoint at generation 1, resume into a
    FRESH Campaign/Explorer, run 2 more — the report fingerprints (and
    curves, corpus digest, violations) equal the uninterrupted 3-gen run."""
    wl, sim = planted
    kw = dict(meta_seed=11, lanes=16, chunk=8, shrink=False, sim=sim)

    full = campaign.Campaign(wl, str(tmp_path / "full"), **kw)
    rep_full = full.run(3)

    part = campaign.Campaign(wl, str(tmp_path / "part"), **kw)
    part.run(1)
    part.checkpoint()
    del part  # the "kill": nothing in-memory survives but the checkpoint

    resumed = campaign.Campaign.resume(
        str(tmp_path / "part"), workload=wl, sim=sim
    )
    assert resumed.generation == 1
    rep_res = resumed.run(2)

    assert rep_res.fingerprint() == rep_full.fingerprint()
    assert rep_res.coverage_curve == rep_full.coverage_curve
    assert rep_res.corpus_curve == rep_full.corpus_curve
    assert rep_res.corpus_digest == rep_full.corpus_digest
    assert rep_res.violations == rep_full.violations
    assert rep_res.seeds_run == rep_full.seeds_run == 48
    # and the checkpoint survives ANOTHER round trip at generation 3
    resumed.checkpoint()
    again = campaign.Campaign.resume(
        str(tmp_path / "part"), workload=wl, sim=sim
    )
    assert again.report().fingerprint() == rep_full.fingerprint()
    # resuming under a different config is refused (hash check)
    import dataclasses as dc

    other = dc.replace(
        wl, config=dc.replace(wl.config, horizon_us=wl.config.horizon_us + 1)
    )
    with pytest.raises(ValueError, match="config hash"):
        campaign.Campaign.resume(str(tmp_path / "part"), workload=other)


@pytest.mark.chaos
def test_explore_out_exports_resumable_campaign(tmp_path, planted, monkeypatch, capsys):
    """Satellite: `python -m madsim_tpu.explore --out DIR` writes the
    campaign on-disk format; the one-shot run resumes as a campaign and
    continues bit-identically."""
    from madsim_tpu import explore

    wl, sim = planted
    # the CLI builds named workloads; point it at the planted one and
    # reuse the compiled sim for the in-process Explorer
    monkeypatch.setattr(explore, "_named_workload", lambda *a: wl)
    orig_init = Explorer.__init__
    monkeypatch.setattr(
        Explorer, "__init__",
        lambda self, *a, **k: orig_init(self, *a, **{**k, "sim": sim}),
    )
    out_dir = str(tmp_path / "oneshot")
    explore.main([
        "--workload", "raft", "--meta-seed", "11", "--lanes", "16",
        "--chunk", "8", "--dispatches", "1", "--no-shrink", "--out",
        out_dir, "--json",
    ])
    line = capsys.readouterr().out.strip().splitlines()[-1]
    cli_report = ExploreReport.from_json(line)
    assert os.path.exists(os.path.join(out_dir, campaign.MANIFEST))
    saved = campaign.load_report(out_dir)
    assert saved.fingerprint() == cli_report.fingerprint()
    # resume the one-shot artifact as a campaign; continuing 2 generations
    # matches an uninterrupted 3-generation explorer bit-for-bit
    c = campaign.Campaign.resume(out_dir, workload=wl, sim=sim)
    rep = c.run(2)
    ex = Explorer(
        wl, meta_seed=11, lanes=16, chunk=8, shrink_violations=False,
        sim=sim,
    )
    assert rep.fingerprint() == ex.run(3).fingerprint()


@pytest.mark.chaos
@pytest.mark.parametrize("meta_seeds", [(1, 2), (5, 9)])
def test_corpus_merge_minimize_preserves_union(tmp_path, planted, meta_seeds):
    """Merging independent campaigns' corpora and cmin-minimizing keeps
    the coverage union EXACTLY (popcount + array equality — also asserted
    inside campaign.minimize itself), keeps only admitted genomes, and
    writes a reloadable merged corpus."""
    from madsim_tpu.explore import popcount_rows

    wl, sim = planted
    dirs = []
    unions = []
    for ms in meta_seeds:
        ex = Explorer(
            wl, meta_seed=ms, lanes=16, chunk=8, shrink_violations=False,
            sim=sim, first_seed=ms * 1000,
        )
        ex.run(2)
        d = str(tmp_path / f"c{ms}")
        campaign.export_explorer(d, ex, workload_ref={"kind": "custom"})
        dirs.append(d)
        unions.append(ex.union.copy())

    entries, manifests = campaign.merge_corpora(dirs)
    assert len({canon_genome(e.cand.key()) for e in entries}) == len(entries)
    out_dir = str(tmp_path / "merged")
    res = campaign.merge_and_minimize(
        dirs, out_dir, workload=wl, sim=sim, lane_width=8
    )
    merged_union = unions[0] | unions[1]
    merged_bits = int(popcount_rows(merged_union[None, :])[0])
    assert res["merged_bits"] == merged_bits > 0
    assert res["kept_bits"] == merged_bits
    kept_union = np.zeros_like(merged_union)
    for e in res["kept"]:
        kept_union |= e.bitmap
    assert np.array_equal(kept_union, merged_union)
    assert 0 < len(res["kept"]) <= len(entries)
    kept_genomes = {canon_genome(e.cand.key()) for e in res["kept"]}
    assert kept_genomes <= {canon_genome(e.cand.key()) for e in entries}
    # the merged corpus reloads to the same kept set, and refuses resume
    reloaded = campaign.load_corpus(out_dir)
    assert {canon_genome(e.cand.key()) for e in reloaded} == kept_genomes
    with pytest.raises(ValueError, match="resume"):
        campaign.Campaign.resume(out_dir, workload=wl, sim=sim)
    # a tampered corpus entry is caught by its per-entry cov_digest...
    doc = reloaded[0].to_dict()
    doc["bitmap"] = ("%08x" % (int(doc["bitmap"][:8], 16) ^ 1)) + doc["bitmap"][8:]
    with pytest.raises(ValueError, match="cov_digest"):
        CorpusEntry.from_dict(doc)
    # ...and a torn/hand-edited corpus FILE by the manifest's sha256
    man = json.load(open(os.path.join(out_dir, campaign.MANIFEST)))
    cpath = os.path.join(out_dir, man["files"]["corpus"])
    with open(cpath) as f:
        text = f.read()
    with open(cpath, "w") as f:
        f.write(text[:-2] + "\n")  # drop a byte: content no longer matches
    with pytest.raises(AssertionError, match="digest"):
        campaign.load_corpus(out_dir)


@pytest.mark.chaos
def test_dedup_collapses_seed_dense_planted_bug(tmp_path, planted):
    """The acceptance contract: the seed-dense planted raft re-stamp bug
    collapses to EXACTLY ONE BugRecord with >= 2 witness seeds; only the
    first witness pays a shrink; the stamped bundle lands in the
    regression corpus and replays green (printing its signature)."""
    from madsim_tpu import triage

    wl, sim = planted
    reg = str(tmp_path / "reg")
    c = campaign.Campaign(
        wl, str(tmp_path / "camp"), meta_seed=0, lanes=64, chunk=64,
        shrink=True, max_shrinks=4, lane_width=16, sim=sim,
        regression_dir=reg,
        spec_ref="tests.test_triage:planted_restamp_spec",
        # pure fresh generations: every violation shares the default-ctl
        # coarse group, which is exactly the seed-dense regime dedup is for
        explorer_kwargs={"fresh_frac": 1.0, "mutant_frac": 0.0},
        # cross-witness causal anatomy (r12): the record's >= 2 witnesses
        # align into one shared event skeleton (docs/causality.md)
        anatomy=True, max_anatomy_witnesses=2,
    )
    for _ in range(4):
        c.run(1)
        if c.bugs and len(c.bugs[0].witnesses) >= 2:
            break
    assert c.bugs, "planted bug not found in 256 fresh seeds"
    assert len(c.bugs) == 1, (
        f"seed-dense bug split into {len(c.bugs)} records: "
        f"{[(b.signature[:12], b.clause_profile) for b in c.bugs]}"
    )
    bug = c.bugs[0]
    assert len(bug.witnesses) >= 2
    assert len(set(bug.witness_seeds)) == len(bug.witnesses)
    assert bug.shrink_error is None
    assert bug.clause_profile, "shrunk profile empty yet chaos-dependent?"
    # only the first witness was shrunk (the whole point of dedup)
    assert c._shrinks_done == 1
    # every witness carries its own coverage digest (per-seed evidence;
    # distinct trajectories => the digests need not coincide)
    assert all(w["cov_digest"] for w in bug.witnesses)
    # cross-witness anatomy: the shared causal-slice skeleton is present,
    # nonempty, and identical for every aligned witness by construction
    # (the per-witness remainder is seed-local noise)
    assert bug.anatomy and "error" not in bug.anatomy, bug.anatomy
    assert bug.anatomy["skeleton"], "witnesses must share a skeleton"
    assert len(bug.anatomy["witnesses"]) == 2
    assert all(w["noise"] >= 0 for w in bug.anatomy["witnesses"])
    # bundle: stamped with signature + campaign provenance, in both dirs
    assert bug.bundle_path and os.path.exists(bug.bundle_path)
    bundle = triage.ReproBundle.load(bug.bundle_path)
    assert bundle.signature == bug.signature
    assert bundle.campaign == c.campaign_id
    assert bundle.generation == bug.first_generation
    reg_path = os.path.join(reg, os.path.basename(bug.bundle_path))
    assert os.path.exists(reg_path)
    # checkpoint -> resume keeps the dedup state (no re-shrink, same record)
    c.checkpoint()
    c2 = campaign.Campaign.resume(
        str(tmp_path / "camp"), workload=wl, sim=sim, regression_dir=reg
    )
    assert [b.signature for b in c2.bugs] == [bug.signature]
    assert c2._shrinks_done == 1
    assert c2.bugs[0].witness_seeds == bug.witness_seeds
    # anatomy (policy + computed skeleton) survives the checkpoint
    assert c2.anatomy is True
    assert c2.bugs[0].anatomy["skeleton_sha"] == \
        bug.anatomy["skeleton_sha"]
    # regression replay: green, and the signature is printed (repro v2)
    printed = []
    rep = campaign.regress(reg, spec=wl.spec, out=printed.append)
    assert rep["bundles"] == 1 and not rep["failures"]
    assert any(bug.signature in line for line in printed)


@pytest.mark.chaos
def test_serve_end_to_end_runs_and_checkpoints_real_campaign(tmp_path, planted):
    """The service loop over a REAL campaign (planted workload via a
    custom factory reusing the module's compiled sim): accepts the queued
    request, streams a fingerprinted report line per slice, checkpoints
    between slices, finishes the request — and the checkpointed state
    equals a direct 2-generation campaign's."""
    wl, sim = planted
    d = str(tmp_path / "svc")
    os.makedirs(os.path.join(d, "queue"))

    def factory(request, campaign_dir, regression_dir, log):
        return campaign.Campaign(
            wl, campaign_dir, meta_seed=11, lanes=16, chunk=8,
            shrink=False, sim=sim, campaign_id=request["id"],
            regression_dir=regression_dir,
        )

    with open(os.path.join(d, "queue", "job1.json"), "w") as f:
        json.dump({"workload": "planted", "generations": 2}, f)
    lines = []
    res = campaign.serve(
        d, slice_generations=1, max_rounds=4, idle_rounds=1,
        out=lambda s: lines.append(json.loads(s)), factory=factory,
        sleep=lambda s: None,
    )
    assert res["completed"] == ["job1"]
    slices = [l for l in lines if "report" in l]
    assert [l["generation"] for l in slices] == [1, 2]
    # the streamed lines reload as reports, fingerprint intact
    for l in slices:
        assert ExploreReport.from_dict(l["report"]).fingerprint() == \
            l["fingerprint"]
    # the time-sliced, checkpointed-every-slice service run equals one
    # uninterrupted 2-generation campaign bit-for-bit
    direct = campaign.Campaign(
        wl, str(tmp_path / "direct"), meta_seed=11, lanes=16, chunk=8,
        shrink=False, sim=sim,
    )
    assert slices[-1]["fingerprint"] == direct.run(2).fingerprint()
    # resume-from-service-checkpoint continues cleanly
    c = campaign.Campaign.resume(
        os.path.join(d, "campaigns", "job1"), workload=wl, sim=sim
    )
    assert c.generation == 2


@pytest.mark.chaos
@pytest.mark.slow
def test_campaign_cross_process_kill_resume(tmp_path):
    """Cross-process acceptance: run 2 generations in one process, resume
    for 2 more in a SECOND process, compare the fingerprint against a
    third process's uninterrupted 4-generation run."""
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        JAX_COMPILATION_CACHE_DIR=f"/tmp/madsim_tpu_jaxcache-{os.getuid()}",
    )

    def run_cli(dir, gens):
        proc = subprocess.run(
            [sys.executable, "-m", "madsim_tpu.campaign", "run",
             "--dir", str(dir), "--workload", "raft",
             "--virtual-secs", "0.5", "--meta-seed", "3", "--lanes", "8",
             "--generations", str(gens), "--no-shrink", "--json"],
            capture_output=True, text=True, timeout=580, env=env, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr[-4000:]
        return json.loads(proc.stdout.strip().splitlines()[-1])

    a1 = run_cli(tmp_path / "resumed", 2)
    assert a1["generation"] == 2
    a2 = run_cli(tmp_path / "resumed", 2)  # same dir: resumes
    assert a2["generation"] == 4
    b = run_cli(tmp_path / "straight", 4)
    assert b["generation"] == 4
    assert a2["fingerprint"] == b["fingerprint"]
    assert a2["report"]["coverage_curve"] == b["report"]["coverage_curve"]
