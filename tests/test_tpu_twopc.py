"""Two-Phase Commit device fuzz (third ProtocolSpec; see tpu/twopc.py).

Mirrors the reference test strategy (SURVEY.md §4): protocol safety as
invariants over fuzzed executions, determinism as a tested property, and
bug-detection validated by injecting the canonical wrong implementation.
"""

import dataclasses
import pytest

import jax
import jax.numpy as jnp
import numpy as np

from madsim_tpu.tpu import BatchedSim, SimConfig, summarize
from madsim_tpu.tpu.spec import replace_handlers
from madsim_tpu.tpu import twopc as tpc
from madsim_tpu.tpu.twopc import make_twopc_spec


def full_chaos(**kw):
    cfg = dict(
        horizon_us=8_000_000,
        msg_capacity=128,  # 2+ slots per origin region: zero overflow
        loss_rate=0.1,
        crash_interval_lo_us=400_000,
        crash_interval_hi_us=2_000_000,
        restart_delay_lo_us=200_000,
        restart_delay_hi_us=1_000_000,
        partition_interval_lo_us=400_000,
        partition_interval_hi_us=1_500_000,
        partition_heal_lo_us=300_000,
        partition_heal_hi_us=1_200_000,
    )
    cfg.update(kw)
    return SimConfig(**cfg)


@pytest.mark.deep
def test_twopc_safe_under_full_chaos():
    """Atomicity + vote respect hold across loss, crashes (coordinator
    included — the blocking case) and partitions, while real work happens
    (transactions keep deciding)."""
    sim = BatchedSim(make_twopc_spec(5), full_chaos())
    state = sim.run(jnp.arange(512), max_steps=60_000)
    s = summarize(state, sim.spec)
    assert s["violations"] == 0
    assert s["deadlocked"] == 0
    assert s["total_overflow"] == 0  # nothing dropped outside loss_rate
    assert s["mean_decided_txns"] > 20  # the fuzz isn't frozen


@pytest.mark.deep
def test_twopc_commits_and_aborts_both_happen():
    """Both outcomes occur across the sweep (vote_yes_p < 1 plus chaos):
    a fuzz that only ever aborts — or only ever commits — tests nothing."""
    sim = BatchedSim(make_twopc_spec(5), full_chaos())
    state = sim.run(jnp.arange(128), max_steps=40_000)
    o_tid = np.asarray(state.node.o_tid)  # [L,N,TXN]
    o_val = np.asarray(state.node.o_val)
    commits = ((o_tid >= 0) & (o_val == tpc.COMMIT)).sum()
    aborts = ((o_tid >= 0) & (o_val == tpc.ABORT)).sum()
    assert commits > 100, int(commits)
    assert aborts > 100, int(aborts)


@pytest.mark.deep
def test_twopc_determinism():
    sim = BatchedSim(make_twopc_spec(5), full_chaos())
    a = sim.run(jnp.arange(32), max_steps=30_000)
    b = sim.run(jnp.arange(32), max_steps=30_000)
    for la, lb in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        assert (np.asarray(la) == np.asarray(lb)).all()


@pytest.mark.deep
def test_twopc_unilateral_abort_bug_caught():
    """The canonical wrong 2PC implementation: an in-doubt participant
    gets impatient and unilaterally aborts instead of running cooperative
    termination. Under chaos the coordinator's COMMIT is delayed past the
    participant's patience — one node aborts a committed transaction and
    the atomicity invariant fires. The correct spec survives the same
    configs (test_twopc_safe_under_full_chaos)."""
    spec = make_twopc_spec(5)

    def impatient_timer(s, nid, now, key):
        from madsim_tpu.tpu import prng

        state, out, timer = spec.on_timer(s, nid, now, key)
        # the oldest unresolved yes-vote, straight from the vote ring
        voted_yes = (s.v_tid >= 0) & (s.v_val == tpc.COMMIT)
        resolved = (
            (s.v_tid[:, None] == s.o_tid[None, :]) & (s.o_tid[None, :] >= 0)
        ).any(-1)
        doubt = voted_yes & ~resolved
        tid = jnp.where(doubt, s.v_tid, jnp.int32(2**30)).min()
        # participants: on a retry tick, flip a coin and give up — record
        # a unilateral local ABORT for the in-doubt txn
        give_up = (nid != 0) & doubt.any() & (prng.uniform(key, 77) < 0.5)
        at = jnp.arange(s.o_tid.shape[0], dtype=jnp.int32) == (
            tid % s.o_tid.shape[0]
        )
        state = state._replace(
            o_tid=jnp.where(give_up & at, tid, state.o_tid),
            o_val=jnp.where(give_up & at, tpc.ABORT, state.o_val),
        )
        return state, out, timer

    buggy = replace_handlers(spec, on_timer=impatient_timer)
    sim = BatchedSim(buggy, full_chaos())
    state = sim.run(jnp.arange(256), max_steps=60_000)
    assert summarize(state)["violations"] > 0


@pytest.mark.deep
def test_twopc_workload_run_batch_smoke():
    """twopc_workload stays wired into run_batch (the kv_workload pattern):
    a small sweep completes clean with nothing dropped outside loss_rate."""
    from madsim_tpu.tpu import run_batch, twopc_workload

    result = run_batch(range(32), twopc_workload(virtual_secs=3.0), max_traces=0)
    assert result.violations == 0
    assert result.summary["total_overflow"] == 0
