"""Production (non-sim) mode tests: the same user-facing APIs — spawn,
time.sleep/timeout, Endpoint, rpc, the gRPC facade — against real sockets
and a real asyncio loop (reference std/ tree, lib.rs:14-23 switch)."""

import asyncio
import os

import pytest

import madsim_tpu as ms
from madsim_tpu import real
from madsim_tpu.net import Endpoint, rpc
from madsim_tpu.sims import grpc


def run(coro):
    return asyncio.run(coro)


def test_real_endpoint_datagram_roundtrip():
    async def main():
        server = await Endpoint.bind("127.0.0.1:0")
        client = await Endpoint.bind("127.0.0.1:0")

        async def receiver():
            data, frm = await server.recv_from(7)
            await server.send_to(frm, 8, data[::-1])

        t = ms.spawn(receiver())
        await client.send_to(server.local_addr(), 7, b"hello")
        data, frm = await client.recv_from(8)
        assert data == b"olleh"
        assert frm == server.local_addr()
        await t
        server.close()
        client.close()
        return True

    assert run(main())


def test_real_sleep_and_timeout():
    async def main():
        t0 = asyncio.get_running_loop().time()
        await ms.time.sleep(0.05)
        assert asyncio.get_running_loop().time() - t0 >= 0.04

        async def slow():
            await ms.time.sleep(5.0)

        with pytest.raises(ms.time.Elapsed):
            await ms.time.timeout(0.05, slow())
        return True

    assert run(main())


@rpc.rpc_request
class Add:
    """Request types must be module-level in production mode (pickle)."""

    def __init__(self, a, b):
        self.a, self.b = a, b


def test_real_rpc_call():
    async def main():
        server = await Endpoint.bind("127.0.0.1:0")

        async def handle(req):
            return req.a + req.b

        rpc.add_rpc_handler(server, Add, handle)
        client = await Endpoint.bind("127.0.0.1:0")
        result = await rpc.call(client, server.local_addr(), Add(20, 22))
        server.close()
        client.close()
        return result

    assert run(main()) == 42


class Greeter(grpc.Service):
    SERVICE_NAME = "helloworld.Greeter"

    @grpc.unary
    async def say_hello(self, request):
        return {"message": f"Hello {request['name']}!"}

    @grpc.unary
    async def whoami(self, request):
        return {"user": grpc.current_metadata().get("user", "<anon>")}

    @grpc.unary
    async def fail(self, request):
        raise grpc.Status.not_found("nope")

    @grpc.server_streaming
    async def count(self, request):
        for i in range(request["n"]):
            yield {"i": i}

    @grpc.client_streaming
    async def sum_all(self, requests):
        total = 0
        async for r in requests:
            total += r["x"]
        return {"sum": total}

    @grpc.bidi_streaming
    async def echo(self, requests):
        async for r in requests:
            yield {"echo": r["x"]}


def test_real_grpc_all_four_shapes():
    async def main():
        server2 = grpc.Server().add_service(Greeter())
        st2 = real.real_spawn(server2.serve("127.0.0.1:50871"))
        await asyncio.sleep(0.2)

        channel = await grpc.connect("http://127.0.0.1:50871")
        stub = grpc.client_for(Greeter, channel)
        assert await stub.say_hello({"name": "world"}) == {"message": "Hello world!"}
        frames = await (await stub.count({"n": 3})).collect()
        assert frames == [{"i": 0}, {"i": 1}, {"i": 2}]
        assert await stub.sum_all([{"x": i} for i in range(5)]) == {"sum": 10}
        out = await (await stub.echo([{"x": "a"}, {"x": "b"}])).collect()
        assert out == [{"echo": "a"}, {"echo": "b"}]

        with pytest.raises(grpc.Status) as e:
            await stub.fail({})
        assert e.value.code == grpc.Code.NOT_FOUND

        def auth(msg, metadata):
            metadata["user"] = "alice"

        ch2 = await grpc.connect("http://127.0.0.1:50871", interceptor=auth)
        stub2 = grpc.client_for(Greeter, ch2)
        assert await stub2.whoami({}) == {"user": "alice"}

        server2.shutdown()
        st2.abort()
        return True

    assert run(main())


def test_real_greeter_example_runs_unmodified():
    # the flagship dual-mode check: examples/greeter.py's Greeter service
    # (written for the sim) served over real sockets
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "examples/greeter_real.py"],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "unary: {'message': 'Hello world!'}" in proc.stdout
    assert "bidi:" in proc.stdout


# -- real-mode parity for the ecosystem sims (VERDICT r2 missing #4): the
# -- reference re-exports the real library outside the sim (etcd lib.rs:1-8,
# -- rdkafka lib.rs:1-10); here the same sim servers/clients run unmodified
# -- over RealEndpoint sockets, like the greeter (examples/greeter_real.py).


def test_real_etcd_kv_put_get():
    from madsim_tpu.sims.etcd import Client, SimServer

    async def main():
        st = real.real_spawn(SimServer.builder().serve("127.0.0.1:21379"))
        await asyncio.sleep(0.3)
        client = await Client.connect("127.0.0.1:21379")
        await client.kv.put("foo", "bar")
        resp = await client.kv.get("foo")
        assert [(kv.key, kv.value) for kv in resp.kvs] == [(b"foo", b"bar")]
        lease = await client.lease.grant(60)
        assert lease.id != 0
        st.abort()
        return True

    assert run(main())


def test_real_kafka_produce_fetch():
    from madsim_tpu.sims.kafka import (
        BaseRecord,
        ClientConfig,
        NewTopic,
        SimBroker,
    )

    async def main():
        bt = real.real_spawn(SimBroker().serve("127.0.0.1:21092"))
        await asyncio.sleep(0.3)
        cfg = ClientConfig(
            {
                "bootstrap.servers": "127.0.0.1:21092",
                "auto.offset.reset": "earliest",
                "group.id": "g1",
            }
        )
        admin = await cfg.create_admin()
        await admin.create_topics([NewTopic("t1", 1)])
        prod = await cfg.create_producer()
        prod.send(BaseRecord.to("t1").with_key(b"k").with_payload(b"hello-kafka"))
        await prod.flush()
        cons = await cfg.create_consumer()
        cons.subscribe(["t1"])
        msg = await cons.poll(timeout=5.0)
        assert msg is not None and msg.payload == b"hello-kafka"
        bt.abort()
        return True

    assert run(main())


def test_real_s3_put_get_object():
    from madsim_tpu.sims.s3 import Client, S3Server

    async def main():
        st = real.real_spawn(S3Server().serve("127.0.0.1:21900"))
        await asyncio.sleep(0.3)
        s3 = await Client.connect("127.0.0.1:21900")
        await s3.create_bucket("b1")
        await s3.put_object("b1", "k1", b"hello-s3")
        assert await s3.get_object("b1", "k1") == b"hello-s3"
        # ranged get over real sockets too (RFC 9110 range handling)
        assert await s3.get_object("b1", "k1", range="bytes=0-4") == b"hello"
        st.abort()
        return True

    assert run(main())


def test_real_uds_backend_datagram_rpc_conn1(monkeypatch, tmp_path):
    """MADSIM_NET_BACKEND=uds: the whole Endpoint surface — tagged
    datagrams, rpc.call, connect1/accept1 — rides Unix domain sockets
    under the same logical addressing (the std/net/mod.rs:33-38 backend
    switch; uds fills the faster-same-host-fabric role of ucx.rs)."""
    monkeypatch.setenv("MADSIM_NET_BACKEND", "uds")
    monkeypatch.setenv("MADSIM_UDS_DIR", str(tmp_path))

    async def main():
        server = await Endpoint.bind("127.0.0.1:0")
        # the logical address maps to a real socket file in MADSIM_UDS_DIR
        host, port = server.local_addr()
        assert (tmp_path / f"{host}_{port}.sock").exists()

        async def serve():
            data, frm = await server.recv_from(7)
            await server.send_to(frm, 8, data.upper())
            tx, rx, _peer = await server.accept1()
            tx.send((await rx.recv()) * 2)
            tx.close()

        async def handle(req):
            return req.a + req.b

        rpc.add_rpc_handler(server, Add, handle)
        t = ms.spawn(serve())

        client = await Endpoint.bind("127.0.0.1:0")
        await client.send_to(server.local_addr(), 7, b"uds")
        data, frm = await client.recv_from(8)
        assert data == b"UDS"
        assert frm == server.local_addr()
        assert await rpc.call(client, server.local_addr(), Add(40, 2)) == 42
        tx, rx, _ = await client.connect1(server.local_addr())
        tx.send(21)
        assert await rx.recv() == 42
        await t
        # rebinding a live address fails like TCP EADDRINUSE (asyncio's
        # start_unix_server alone would silently hijack the path)
        with pytest.raises(OSError, match="address already in use"):
            await Endpoint.bind(f"{host}:{port}")
        server.close()
        client.close()
        # close() removes the socket file
        assert not (tmp_path / f"{host}_{port}.sock").exists()
        return True

    assert run(main())


def test_real_uds_backend_grpc(monkeypatch, tmp_path):
    """The gRPC facade works unmodified over the uds backend (transport
    selection is invisible above the Endpoint layer)."""
    monkeypatch.setenv("MADSIM_NET_BACKEND", "uds")
    monkeypatch.setenv("MADSIM_UDS_DIR", str(tmp_path))

    async def main():
        server = grpc.Server().add_service(Greeter())
        st = real.real_spawn(server.serve("127.0.0.1:50993"))
        await asyncio.sleep(0.2)
        channel = await grpc.connect("http://127.0.0.1:50993")
        client = grpc.client_for(Greeter, channel)
        reply = await client.say_hello({"name": "uds"})
        assert (tmp_path / "127.0.0.1_50993.sock").exists()
        server.shutdown()
        st.abort()
        return reply

    assert run(main())["message"] == "Hello uds!"


def test_rpc_bench_harness_smoke():
    """benches/rpc_bench.py (the madsim/benches/rpc.rs analog) runs end to
    end on both transports and emits well-formed JSON rows."""
    import json
    import pathlib
    import subprocess
    import sys

    bench = pathlib.Path(__file__).resolve().parents[1] / "benches" / "rpc_bench.py"
    proc = subprocess.run(
        [sys.executable, str(bench), "--rounds", "5"],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    rows = [json.loads(line) for line in proc.stdout.strip().splitlines()]
    benches = {(r["backend"], r["bench"]) for r in rows}
    for be in ("tcp", "uds"):
        assert (be, "rpc_latency_empty") in benches
        assert (be, "rpc_throughput_1048576B") in benches


def test_real_shm_backend_bulk_data_plane(monkeypatch, tmp_path):
    """MADSIM_NET_BACKEND=shm: uds doorbell + shared-memory rings for bulk
    frames (the same-host analog of the reference's RDMA-class fabrics,
    std/net/ucx.rs / erpc.rs). Large payloads must round-trip through the
    ring (and keep working when the ring overflows — inline fallback),
    small ones inline; conn1 is duplex over two rings."""
    monkeypatch.setenv("MADSIM_NET_BACKEND", "shm")
    monkeypatch.setenv("MADSIM_UDS_DIR", str(tmp_path))
    monkeypatch.setenv("MADSIM_SHM_RING", str(64 * 1024))  # small: force wrap

    async def main():
        server = await Endpoint.bind("127.0.0.1:0")

        async def serve():
            for _ in range(6):
                data, frm = await server.recv_from(7)
                await server.send_to(frm, 8, bytes(reversed(data)))
            tx, rx, _peer = await server.accept1()
            blob = await rx.recv()
            tx.send(blob + blob)  # big reply rides the reverse ring
            tx.close()

        t = ms.spawn(serve())
        client = await Endpoint.bind("127.0.0.1:0")
        # mix of sizes: inline (<256B), ring-sized, ring-overflow (>cap)
        for size in (16, 1024, 32 * 1024, 100 * 1024, 8 * 1024, 50 * 1024):
            payload = bytes(range(256)) * (size // 256) or b"x" * size
            await client.send_to(server.local_addr(), 7, payload)
            data, _ = await client.recv_from(8)
            assert data == bytes(reversed(payload)), size
        tx, rx, _ = await client.connect1(server.local_addr())
        blob = os.urandom(40 * 1024)
        tx.send(blob)
        assert await rx.recv() == blob + blob
        await t
        server.close()
        client.close()
        return True

    assert run(main())


def test_real_bytes_codec_no_pickle_on_the_wire(monkeypatch, tmp_path):
    """MADSIM_NET_CODEC=bytes: raw-bytes framing — safe across trust
    boundaries (no pickle.loads on network input). Bytes datagrams and
    conn1 streams work; object payloads are rejected loudly."""
    monkeypatch.setenv("MADSIM_NET_CODEC", "bytes")

    async def main():
        server = await Endpoint.bind("127.0.0.1:0")

        async def serve():
            data, frm = await server.recv_from(7)
            await server.send_to(frm, 8, data.upper())
            tx, rx, _peer = await server.accept1()
            tx.send((await rx.recv()) * 2)
            tx.close()

        t = ms.spawn(serve())
        client = await Endpoint.bind("127.0.0.1:0")
        await client.send_to(server.local_addr(), 7, b"bytes-codec")
        data, _ = await client.recv_from(8)
        assert data == b"BYTES-CODEC"
        tx, rx, _ = await client.connect1(server.local_addr())
        tx.send(b"ab")
        assert await rx.recv() == b"abab"
        # objects are refused at the SENDING side, before touching the wire
        with pytest.raises(TypeError, match="bytes payloads only"):
            await client.send_to_raw(server.local_addr(), 7, {"not": "bytes"})
        await t
        server.close()
        client.close()
        return True

    assert run(main())


def test_real_shm_plus_bytes_codec_compose(monkeypatch, tmp_path):
    # the two compose: shared-memory data plane with no pickle anywhere.
    # (NB the trust stories differ: bytes-codec-over-tcp is the
    # cross-trust wire; shm itself is a same-USER fabric — 0700 socket
    # dir, 0600 segments — see real/shm.py)
    monkeypatch.setenv("MADSIM_NET_BACKEND", "shm")
    monkeypatch.setenv("MADSIM_UDS_DIR", str(tmp_path))
    monkeypatch.setenv("MADSIM_NET_CODEC", "bytes")

    async def main():
        server = await Endpoint.bind("127.0.0.1:0")

        async def serve():
            data, frm = await server.recv_from(1)
            await server.send_to(frm, 2, data[::-1])

        t = ms.spawn(serve())
        client = await Endpoint.bind("127.0.0.1:0")
        blob = os.urandom(64 * 1024)
        await client.send_to(server.local_addr(), 1, blob)
        data, _ = await client.recv_from(2)
        assert data == blob[::-1]
        await t
        server.close()
        client.close()
        return True

    assert run(main())
