"""etcd sim tests — mirrors reference madsim-etcd-client/tests/test.rs:
kv (:9-61), lease (:63-127), election (:129-241), maintenance (:243-263),
load_dump (:265-314), plus kill/restart-with-snapshot chaos and prefix watch.
"""

import pytest

import madsim_tpu as ms
from madsim_tpu.sims import etcd
from madsim_tpu.sims.etcd import Client, SimServer
from madsim_tpu.sims.etcd.service import Compare, Txn, TxnOp


def make_rt(seed=1):
    rt = ms.Runtime(seed=seed)
    state = {}

    async def setup():
        h = rt.handle
        state["server"] = (
            h.create_node().name("server").ip("10.0.0.1")
            .init(lambda: SimServer.builder().serve("10.0.0.1:2379"))
            .build()
        )
        state["client"] = h.create_node().name("client").ip("10.0.0.2").build()
        ms.net.NetSim.current().add_dns_record("etcd", "10.0.0.1")
        await ms.time.sleep(1.0)

    return rt, state, setup


def test_kv():
    rt, state, setup = make_rt()

    async def main():
        await setup()

        async def run():
            client = await Client.connect(["etcd:2379"])
            kv = client.kv_client()
            await kv.put("foo", "bar")
            resp = await kv.get("foo")
            k = resp.kvs[0]
            revision = resp.header.revision
            assert k.key == b"foo"
            assert k.value == b"bar"
            assert k.lease == 0
            assert k.create_revision == revision
            assert k.mod_revision == revision
            # put again: create_revision sticks, mod_revision advances
            await kv.put("foo", "gg")
            resp = await kv.get("foo")
            k = resp.kvs[0]
            assert k.value == b"gg"
            assert k.create_revision == revision
            assert k.mod_revision == resp.header.revision
            # delete
            await kv.delete("foo")
            assert (await kv.get("foo")).kvs == []
            # error: request too large (2 MiB > 1.5 MiB cap)
            with pytest.raises(etcd.EtcdError, match="request is too large"):
                await kv.put("large", b"\x01" * 0x20_0000)
            return True

        return await state["client"].spawn(run())

    assert rt.block_on(main())


def test_txn():
    rt, state, setup = make_rt()

    async def main():
        await setup()

        async def run():
            client = await Client.connect("10.0.0.1:2379")
            kv = client.kv
            await kv.put("k", "1")
            # success branch
            resp = await kv.txn(
                Txn()
                .when(Compare.value_eq("k", "1"))
                .and_then(TxnOp.put("k", "2"), TxnOp.get("k"))
                .or_else(TxnOp.put("k", "fail"))
            )
            assert resp.succeeded
            assert resp.op_responses[1].kvs[0].value == b"2"
            # failure branch
            resp = await kv.txn(
                Txn()
                .when(Compare.value_eq("k", "1"))
                .and_then(TxnOp.put("k", "nope"))
                .or_else(TxnOp.delete("k"))
            )
            assert not resp.succeeded
            assert (await kv.get("k")).kvs == []

            # the whole txn is ONE revision: inner writes share it, and the
            # next plain write gets a strictly higher one (no duplicate
            # mod_revisions — diverges from the reference's rewind bug)
            resp = await kv.txn(
                Txn().and_then(TxnOp.put("t1", "a"), TxnOp.put("t2", "b"))
            )
            r1 = (await kv.get("t1")).kvs[0].mod_revision
            r2 = (await kv.get("t2")).kvs[0].mod_revision
            assert r1 == r2 == resp.header.revision
            after = await kv.put("t3", "c")
            assert after.header.revision > resp.header.revision
            return True

        return await state["client"].spawn(run())

    assert rt.block_on(main())


def test_lease():
    rt, state, setup = make_rt()

    async def main():
        await setup()

        async def run():
            client = await Client.connect("10.0.0.1:2379")
            kv, lease = client.kv, client.lease
            granted = await lease.grant(60)
            await kv.put("foo", "bar", etcd.PutOptions().with_lease(granted.id))
            resp = await kv.get("foo")
            assert resp.kvs[0].lease == granted.id
            # list leases
            resp = await lease.leases()
            assert [s.id for s in resp.leases] == [granted.id]

            # keep alive for 90s total
            await ms.time.sleep(45.0)
            keeper, responses = await lease.keep_alive(granted.id)
            await ms.time.sleep(45.0)
            await keeper.keep_alive()
            resp = await responses.message()
            assert resp.id == granted.id
            assert 50 < resp.ttl <= 60
            assert (await kv.get("foo")).kvs  # still alive

            # wait for expiry: key deleted
            await ms.time.sleep(61.0)
            assert (await kv.get("foo")).kvs == []

            # errors on unknown lease
            with pytest.raises(etcd.EtcdError, match="lease not found"):
                await kv.put("foo", "bar", etcd.PutOptions().with_lease(1))
            with pytest.raises(etcd.EtcdError, match="lease not found"):
                await lease.revoke(1)
            with pytest.raises(etcd.EtcdError, match="lease not found"):
                await lease.time_to_live(1)
            return True

        return await state["client"].spawn(run())

    assert rt.block_on(main())


def test_election():
    rt, state, setup = make_rt()

    async def main():
        await setup()
        h = rt.handle
        c2 = h.create_node().name("client2").ip("10.0.0.3").build()
        c3 = h.create_node().name("client3").ip("10.0.0.4").build()

        async def first_leader():
            client = await Client.connect("10.0.0.1:2379")
            await ms.time.sleep(5.0)  # let the observer subscribe
            lease = await client.lease.grant(60)
            resp = await client.election.campaign("leader", "1", lease.id)
            leader_key = resp.leader
            assert leader_key.name == b"leader"
            assert leader_key.lease == lease.id
            resp = await client.election.leader("leader")
            assert resp.kv.value == b"1"
            # campaign again completes immediately
            await client.election.campaign("leader", "1", lease.id)
            # campaign with a new value
            await client.election.campaign("leader", "1.1", lease.id)
            # proclaim
            await client.election.proclaim("1.2", leader_key)
            resp = await client.election.leader("leader")
            assert resp.kv.value == b"1.2"
            await ms.time.sleep(30.0)
            # revoking the lease releases leadership
            await client.lease.revoke(lease.id)
            with pytest.raises(etcd.EtcdError, match="session expired"):
                await client.election.proclaim("1.3", leader_key)
            # campaign with an invalid lease
            with pytest.raises(etcd.EtcdError, match="lease not found"):
                await client.election.campaign("invalid_lease", "1", 1)
            return True

        async def second_leader():
            client = await Client.connect("10.0.0.1:2379")
            await ms.time.sleep(10.0)  # after client1 is leader
            lease = await client.lease.grant(60)
            # blocks until client1's lease is revoked
            resp = await client.election.campaign("leader", "2", lease.id)
            assert resp.leader.name == b"leader"
            assert resp.leader.lease == lease.id
            await client.election.resign(resp.leader)
            return True

        async def observer():
            client = await Client.connect("10.0.0.1:2379")
            stream = await client.election.observe("leader")
            values = []
            for _ in range(3):
                resp = await stream.message()
                values.append(resp.kv.value)
            assert values == [b"1", b"1.1", b"1.2"]
            await ms.time.sleep(15.0)
            # two election keys live under the prefix now
            resp = await client.kv.get("leader", prefix=True)
            assert len(resp.kvs) == 2
            resp = await stream.message()
            assert resp.kv.value == b"2"
            return True

        t1 = state["client"].spawn(first_leader())
        t2 = c2.spawn(second_leader())
        t3 = c3.spawn(observer())
        return await t1 and await t2 and await t3

    assert rt.block_on(main())


def test_watch_prefix_events():
    rt, state, setup = make_rt()

    async def main():
        await setup()

        async def run():
            client = await Client.connect("10.0.0.1:2379")
            stream = await client.watch.watch_prefix("app/")
            await client.kv.put("app/a", "1")
            await client.kv.put("other", "x")  # not under the prefix
            await client.kv.put("app/b", "2")
            await client.kv.delete("app/a")
            e1 = await stream.message()
            assert (e1.type, e1.kv.key, e1.kv.value) == (
                etcd.EventType.PUT, b"app/a", b"1",
            )
            e2 = await stream.message()
            assert (e2.type, e2.kv.key) == (etcd.EventType.PUT, b"app/b")
            e3 = await stream.message()
            assert (e3.type, e3.kv.key) == (etcd.EventType.DELETE, b"app/a")
            return True

        return await state["client"].spawn(run())

    assert rt.block_on(main())


def test_maintenance_status():
    rt, state, setup = make_rt()

    async def main():
        await setup()

        async def run():
            client = await Client.connect("10.0.0.1:2379")
            await client.maintenance.status()
            return True

        return await state["client"].spawn(run())

    assert rt.block_on(main())


def test_load_dump():
    # mirror test.rs:265-314: dump with binary values, re-serve, read back
    rt, state, setup = make_rt()

    async def main():
        await setup()
        h = rt.handle

        async def phase1():
            client = await Client.connect("10.0.0.1:2379")
            lease = await client.lease.grant(60)
            await client.kv.put(
                "foo", b"bar\xff\x01\x02", etcd.PutOptions().with_lease(lease.id)
            )
            return await client.dump()

        dump = await state["client"].spawn(phase1())

        async def serve2():
            await SimServer.builder().load(dump).serve("10.0.0.1:2380")

        state["server"].spawn(serve2())
        await ms.time.sleep(1.0)

        async def phase2():
            client = await Client.connect("10.0.0.1:2380")
            resp = await client.kv.get("foo")
            assert resp.kvs[0].value == b"bar\xff\x01\x02"
            assert resp.kvs[0].lease != 0
            return True

        return await state["client"].spawn(phase2())

    assert rt.block_on(main())


def test_server_kill_restart_with_snapshot():
    """The chaos pattern the reference uses at test.rs:199-254: periodically
    dump, kill the server, restart it from the last snapshot, and verify
    clients reconnect and see the snapshotted state."""
    rt = ms.Runtime(seed=7)

    async def main():
        h = rt.handle
        snapshots = {}

        def serve():
            if "dump" in snapshots:
                return SimServer.builder().load(snapshots["dump"]).serve(
                    "10.0.0.1:2379"
                )
            return SimServer.builder().serve("10.0.0.1:2379")

        server = (
            h.create_node().name("server").ip("10.0.0.1").init(serve).build()
        )
        client_node = h.create_node().name("client").ip("10.0.0.2").build()
        await ms.time.sleep(1.0)

        async def run():
            client = await Client.connect("10.0.0.1:2379")
            await client.kv.put("stable", "before-crash")
            snapshots["dump"] = await client.dump()

            h.kill(server.id)
            await ms.time.sleep(1.0)
            h.restart(server.id)  # re-runs init => serves from snapshot
            await ms.time.sleep(1.0)

            client = await Client.connect("10.0.0.1:2379")
            resp = await client.kv.get("stable")
            assert resp.kvs[0].value == b"before-crash"
            # and the restarted server accepts new writes
            await client.kv.put("after", "restart")
            assert (await client.kv.get("after")).kvs[0].value == b"restart"
            return True

        return await client_node.spawn(run())

    assert rt.block_on(main())


def test_injected_timeouts():
    rt = ms.Runtime(seed=3)

    async def main():
        h = rt.handle
        h.create_node().name("server").ip("10.0.0.1").init(
            lambda: SimServer.builder().timeout_rate(0.5).serve("10.0.0.1:2379")
        ).build()
        client_node = h.create_node().name("client").ip("10.0.0.2").build()
        await ms.time.sleep(1.0)

        async def run():
            client = await Client.connect("10.0.0.1:2379")
            timeouts = 0
            for i in range(20):
                try:
                    await client.kv.put(f"k{i}", "v")
                except etcd.EtcdError as e:
                    assert "timed out" in str(e)
                    timeouts += 1
            assert 0 < timeouts < 20  # some injected, some pass
            return True

        return await client_node.spawn(run())

    assert rt.block_on(main())


def test_get_with_revision_historical_reads():
    """MVCC historical reads: get(revision=N) serves the store as of
    revision N — implemented where the reference panics todo!()
    (service.rs:325) — with real etcd's error shapes at the edges."""
    rt, state, setup = make_rt(seed=77)

    async def main():
        await setup()

        async def run():
            client = await Client.connect("etcd:2379")
            kv = client.kv
            r1 = (await kv.put("k", "v1")).header.revision
            r2 = (await kv.put("k", "v2")).header.revision
            await kv.delete("k")
            await kv.put("k", "v4")

            async def value_at(rev):
                rsp = await kv.get("k", etcd.GetOptions(revision=rev))
                return rsp.kvs[0].value if rsp.kvs else None

            assert await value_at(r1) == b"v1"
            assert await value_at(r2) == b"v2"
            assert await value_at(r2 + 1) is None  # deleted at that revision
            # current read unaffected
            assert (await kv.get("k")).kvs[0].value == b"v4"
            # prefix historical read
            await kv.put("p/a", "1")
            rp = (await kv.put("p/b", "2")).header.revision
            await kv.delete("p/a")
            rsp = await kv.get("p/", etcd.GetOptions(prefix=True, revision=rp))
            assert [e.value for e in rsp.kvs] == [b"1", b"2"]
            # future revision errors like real etcd
            with pytest.raises(etcd.EtcdError, match="future revision"):
                await kv.get("k", etcd.GetOptions(revision=10_000))
            # proclaim() is a write path too: its update must be visible
            # at its own revision (review-found miss in the MVCC wiring)
            lease = await client.lease.grant(60)
            camp = await client.election.campaign("boss", "v1", lease.id)
            await client.election.proclaim("v2", camp.leader)
            hdr_rev = (await kv.put("tick", "x")).header.revision
            hist = await kv.get(
                bytes(camp.leader.key), etcd.GetOptions(revision=hdr_rev)
            )
            assert hist.kvs and hist.kvs[0].value == b"v2"
            return True

        return await state["client"].spawn(run())

    assert rt.block_on(main())


def test_get_with_revision_compacted_after_snapshot_restore():
    """A snapshot load() is a compaction point: historical reads below it
    raise 'compacted' (real etcd restore semantics); at or above it they
    serve from the re-seeded history."""
    rt, state, setup = make_rt(seed=78)

    async def main():
        await setup()

        async def phase1():
            client = await Client.connect("10.0.0.1:2379")
            await client.kv.put("a", "1")
            r2 = (await client.kv.put("a", "2")).header.revision
            return r2, await client.dump()

        r2, dump = await state["client"].spawn(phase1())

        async def serve2():
            await SimServer.builder().load(dump).serve("10.0.0.1:2380")

        state["server"].spawn(serve2())
        await ms.time.sleep(1.0)

        async def phase2():
            client = await Client.connect("10.0.0.1:2380")
            rsp = await client.kv.get("a", etcd.GetOptions(revision=r2))
            assert rsp.kvs[0].value == b"2"
            with pytest.raises(etcd.EtcdError, match="compacted"):
                await client.kv.get("a", etcd.GetOptions(revision=r2 - 1))
            # new writes extend history past the compaction point
            r3 = (await client.kv.put("a", "3")).header.revision
            rsp = await client.kv.get("a", etcd.GetOptions(revision=r3))
            assert rsp.kvs[0].value == b"3"
            return True

        return await state["client"].spawn(phase2())

    assert rt.block_on(main())
