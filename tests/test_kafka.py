"""Kafka sim tests — mirrors reference madsim-rdkafka/tests/test.rs: a
broker node, an admin creating a 3-partition topic, two producers, and two
consumers (Base + Stream) splitting the partitions; totals must match.
Plus broker-level unit tests for offsets/watermarks/size caps."""

import pytest

import madsim_tpu as ms
from madsim_tpu.sims import kafka
from madsim_tpu.sims.kafka import (
    AdminClient,
    BaseRecord,
    Broker,
    ClientConfig,
    FetchOptions,
    KafkaError,
    NewTopic,
    OwnedRecord,
    SimBroker,
    TopicPartitionList,
)
from madsim_tpu.sims.kafka.tpl import OFFSET_BEGINNING, OFFSET_INVALID


def test_broker_produce_fetch_roundtrip():
    b = Broker()
    b.create_topic("t", 3)
    for i in range(9):
        b.produce([OwnedRecord(topic="t", payload=bytes([i]))])
    # keyless records round-robin over 3 partitions
    assert [p.log_end_offset for p in b.topics["t"].partitions] == [3, 3, 3]

    tpl = TopicPartitionList()
    for p in range(3):
        tpl.add_partition_offset("t", p, OFFSET_BEGINNING)
    msgs = b.fetch(tpl)
    assert len(msgs) == 9
    # offsets advanced: nothing more to fetch
    assert b.fetch(tpl) == []
    # explicit partition wins
    b.produce([OwnedRecord(topic="t", partition=2, payload=b"x")])
    assert b.topics["t"].partitions[2].log_end_offset == 4
    # keyed records are stable
    b.produce([OwnedRecord(topic="t", key=b"k", payload=b"a")])
    b.produce([OwnedRecord(topic="t", key=b"k", payload=b"b")])
    import zlib

    kp = zlib.crc32(b"k") % 3
    part_msgs = b.topics["t"].partitions[kp].msgs
    assert [m.payload for m in part_msgs[-2:]] == [b"a", b"b"]


def test_broker_watermarks_and_times():
    b = Broker()
    b.create_topic("t", 1)
    for i, ts in enumerate([100, 200, 300]):
        b.produce([OwnedRecord(topic="t", payload=bytes([i]), timestamp=ts)])
    assert b.fetch_watermarks("t", 0) == (0, 3)
    tpl = TopicPartitionList()
    tpl.add_partition_offset("t", 0, 150)  # timestamp query
    out = b.offsets_for_times(tpl)
    assert out.list[0].offset == 1  # earliest ts >= 150 is offset 1
    tpl2 = TopicPartitionList()
    tpl2.add_partition_offset("t", 0, 999)
    assert b.offsets_for_times(tpl2).list[0].offset == OFFSET_INVALID


def test_broker_fetch_size_caps():
    b = Broker()
    b.create_topic("t", 1)
    for i in range(10):
        b.produce([OwnedRecord(topic="t", payload=b"x" * 100)])
    tpl = TopicPartitionList()
    tpl.add_partition_offset("t", 0, OFFSET_BEGINNING)
    msgs = b.fetch(tpl, FetchOptions(fetch_max_bytes=350))
    assert len(msgs) == 3  # 4th record would exceed the cap
    msgs = b.fetch(tpl, FetchOptions(fetch_max_bytes=10_000))
    assert len(msgs) == 7  # resumes where the tpl left off


def test_broker_errors():
    b = Broker()
    with pytest.raises(KafkaError, match="unknown topic"):
        b.produce([OwnedRecord(topic="nope", payload=b"")])
    b.create_topic("t", 1)
    with pytest.raises(KafkaError, match="unknown partition"):
        b.fetch_watermarks("t", 5)
    tpl = TopicPartitionList()
    tpl.add_partition("t", 0)  # OFFSET_INVALID
    b.produce([OwnedRecord(topic="t", payload=b"x")])
    with pytest.raises(KafkaError, match="no offset"):
        b.fetch(tpl)


def test_cluster_producers_consumers():
    """The reference's flagship test (tests/test.rs): 2 producers x 30
    records into 3 partitions; BaseConsumer takes partitions 0+1, a
    StreamConsumer takes partition 2; every payload is consumed once."""
    rt = ms.Runtime(seed=11)

    async def main():
        h = rt.handle
        broker = h.create_node().name("broker").ip("10.0.0.1").init(
            lambda: SimBroker().serve("10.0.0.1:9092")
        ).build()
        ms.net.NetSim.current().add_dns_record("broker", "10.0.0.1")
        await ms.time.sleep(1.0)

        cfg = lambda: ClientConfig({"bootstrap.servers": "broker:9092"})

        admin_node = h.create_node().name("admin").ip("10.0.0.2").build()

        async def admin():
            client = await cfg().create_admin()
            await client.create_topics([NewTopic("topic", 3)])

        await admin_node.spawn(admin())

        async def producer(pid, count, interval):
            p = await cfg().create_producer()
            for i in range(1, count + 1):
                p.send(
                    BaseRecord.to("topic")
                    .with_key(f"{pid}.{i}")
                    .with_payload(bytes([i]))
                )
                await ms.time.sleep(interval)
                if i % 10 == 0:
                    await p.flush()
            await p.flush()

        p1 = h.create_node().name("producer-1").ip("10.0.1.1").build()
        p2 = h.create_node().name("producer-2").ip("10.0.1.2").build()
        t1 = p1.spawn(producer(1, 30, 0.1))
        t2 = p2.spawn(producer(2, 30, 0.2))

        seen = []

        async def base_consumer():
            c = await cfg().create_consumer()
            tpl = TopicPartitionList()
            tpl.add_partition("topic", 0)
            tpl.add_partition("topic", 1)
            c.assign(tpl)
            while True:
                msg = await c.poll()
                if msg is None:
                    await ms.time.sleep(0.1)
                    continue
                seen.append(msg.payload[0])

        async def stream_consumer():
            c = await cfg().create_stream_consumer()
            tpl = TopicPartitionList()
            tpl.add_partition("topic", 2)
            c.assign(tpl)
            async for msg in c.stream():
                seen.append(msg.payload[0])

        c1 = h.create_node().name("consumer-1").ip("10.0.2.1").build()
        c2 = h.create_node().name("consumer-2").ip("10.0.2.2").build()
        c1.spawn(base_consumer())
        c2.spawn(stream_consumer())

        await t1
        await t2
        await ms.time.sleep(5.0)
        return seen

    seen = rt.block_on(main())
    assert len(seen) == 60
    assert sum(seen) == 2 * sum(range(1, 31))


def test_subscribe_discovers_partitions():
    rt = ms.Runtime(seed=3)

    async def main():
        h = rt.handle
        h.create_node().name("broker").ip("10.0.0.1").init(
            lambda: SimBroker().serve("10.0.0.1:9092")
        ).build()
        client_node = h.create_node().name("client").ip("10.0.0.2").build()
        await ms.time.sleep(1.0)

        async def run():
            cfg = ClientConfig({"bootstrap.servers": "10.0.0.1:9092"})
            admin = await cfg.create_admin()
            await admin.create_topics([NewTopic("logs", 4)])

            p = await cfg.create_producer()
            for i in range(8):
                p.send(BaseRecord.to("logs").with_payload(bytes([i])))
            await p.flush()

            c = await cfg.create_consumer()
            c.subscribe(["logs"])
            got = []
            while len(got) < 8:
                msg = await c.poll()
                if msg is None:
                    await ms.time.sleep(0.05)
                    continue
                got.append(msg.payload[0])
            assert sorted(got) == list(range(8))

            # metadata sees all four partitions
            meta = await c.fetch_metadata("logs")
            assert meta == {"logs": [0, 1, 2, 3]}
            return True

        return await client_node.spawn(run())

    assert rt.block_on(main())


def test_latest_offset_reset_skips_history():
    rt = ms.Runtime(seed=5)

    async def main():
        h = rt.handle
        h.create_node().name("broker").ip("10.0.0.1").init(
            lambda: SimBroker().serve("10.0.0.1:9092")
        ).build()
        client_node = h.create_node().name("client").ip("10.0.0.2").build()
        await ms.time.sleep(1.0)

        async def run():
            cfg = ClientConfig({"bootstrap.servers": "10.0.0.1:9092"})
            admin = await cfg.create_admin()
            await admin.create_topics([NewTopic("t", 1)])
            p = await cfg.create_producer()
            for i in range(5):
                p.send(BaseRecord.to("t").with_payload(bytes([i])))
            await p.flush()

            late = await cfg.set("auto.offset.reset", "latest").create_consumer()
            tpl = TopicPartitionList()
            tpl.add_partition("t", 0)
            late.assign(tpl)
            first = await late.poll()
            # "latest" starts at the final existing record
            assert first is not None and first.payload == bytes([4])
            return True

        return await client_node.spawn(run())

    assert rt.block_on(main())


def test_producer_transactions():
    """Transactional produce (producer.rs:246-320): init/begin/commit ships
    the buffer as one atomic batch; abort discards it; state errors match
    the reference's InvalidTransactionalState cases."""
    rt = ms.Runtime(seed=21)

    async def main():
        h = rt.handle
        h.create_node().name("broker").ip("10.0.0.1").init(
            lambda: SimBroker().serve("10.0.0.1:9092")
        ).build()
        await ms.time.sleep(1.0)

        client_node = h.create_node().name("client").ip("10.0.0.2").build()

        async def body():
            cfg = ClientConfig(
                {
                    "bootstrap.servers": "10.0.0.1:9092",
                    "transactional.id": "tx-1",
                    "auto.offset.reset": "earliest",
                    "group.id": "g",
                }
            )
            await (await cfg.create_admin()).create_topics([NewTopic("t", 1)])

            p = await cfg.create_producer()
            # state machine errors (producer.rs:266-284)
            with pytest.raises(kafka.KafkaError, match="not initialized"):
                p.begin_transaction()
            await p.init_transactions()
            with pytest.raises(kafka.KafkaError, match="before any operations"):
                await p.init_transactions()
            with pytest.raises(kafka.KafkaError, match="transaction is active"):
                p.send(BaseRecord.to("t").with_payload(b"outside"))

            # aborted transaction: nothing reaches the broker
            p.begin_transaction()
            p.send(BaseRecord.to("t").with_payload(b"doomed-1"))
            p.send(BaseRecord.to("t").with_payload(b"doomed-2"))
            await p.flush()  # no-op for txn producers: nothing ships early
            await p.abort_transaction()

            # committed transaction: the whole batch lands atomically
            p.begin_transaction()
            for i in range(3):
                p.send(BaseRecord.to("t").with_payload(b"keep-%d" % i))
            await p.commit_transaction()

            c = await cfg.create_consumer()
            c.subscribe(["t"])
            seen = []
            for _ in range(3):
                msg = await c.poll(timeout=5.0)
                seen.append(msg.payload)
            assert seen == [b"keep-0", b"keep-1", b"keep-2"]
            assert await c.poll(timeout=0.5) is None  # no doomed-* leaked
            with pytest.raises(kafka.KafkaError, match="no opened transaction"):
                await p.commit_transaction()
            return True

        assert await client_node.spawn(body())

        # a producer without transactional.id cannot init (producer.rs:249)
        async def no_tid():
            p = await ClientConfig(
                {"bootstrap.servers": "10.0.0.1:9092"}
            ).create_producer()
            with pytest.raises(kafka.KafkaError, match="transactional ID"):
                await p.init_transactions()
            return True

        assert await client_node.spawn(no_tid())

    rt.block_on(main())


def test_admin_create_partitions():
    """NewPartitions grows a topic; shrinking is rejected (admin.rs:184-208)."""
    rt = ms.Runtime(seed=22)

    async def main():
        h = rt.handle
        h.create_node().name("broker").ip("10.0.0.1").init(
            lambda: SimBroker().serve("10.0.0.1:9092")
        ).build()
        await ms.time.sleep(1.0)
        node = h.create_node().name("client").ip("10.0.0.2").build()

        async def body():
            cfg = ClientConfig({"bootstrap.servers": "10.0.0.1:9092"})
            admin = await cfg.create_admin()
            await admin.create_topics([NewTopic("t", 2)])
            await admin.create_partitions([kafka.NewPartitions("t", 5)])
            consumer = await cfg.create_consumer()
            meta = await consumer.fetch_metadata("t")
            assert meta == {"t": [0, 1, 2, 3, 4]}
            with pytest.raises(kafka.KafkaError, match="cannot shrink"):
                await admin.create_partitions([kafka.NewPartitions("t", 3)])
            with pytest.raises(kafka.KafkaError, match="unknown topic"):
                await admin.create_partitions([kafka.NewPartitions("nope", 9)])
            return True

        assert await node.spawn(body())

    rt.block_on(main())
