"""gRPC facade tests — mirrors tonic-example/tests/test.rs:
all 4 RPC shapes (:22-119), server_crash (:234-278), client_crash (:155-202),
interceptors + timeouts (:316-400)."""

import pytest

import madsim_tpu as ms
from madsim_tpu.sims import grpc


class Greeter(grpc.Service):
    SERVICE_NAME = "helloworld.Greeter"

    @grpc.unary
    async def say_hello(self, request):
        return {"message": f"Hello {request['name']}!"}

    @grpc.unary
    async def whoami(self, request):
        md = grpc.current_metadata()
        return {"user": md.get("user", "<anon>")}

    @grpc.unary
    async def slow(self, request):
        await ms.time.sleep(10.0)
        return {"message": "finally"}

    @grpc.unary
    async def slow_whoami(self, request):
        # read metadata only AFTER an await: interleaved concurrent requests
        # must still each see their own metadata
        await ms.time.sleep(request.get("delay", 0.5))
        md = grpc.current_metadata()
        return {"user": md.get("user", "<anon>")}

    @grpc.unary
    async def fail_not_found(self, request):
        raise grpc.Status.not_found("no such thing")

    @grpc.unary
    async def crash_handler(self, request):
        raise RuntimeError("handler bug")

    @grpc.server_streaming
    async def count(self, request):
        for i in range(request["n"]):
            await ms.time.sleep(0.05)
            yield {"i": i}

    @grpc.client_streaming
    async def sum_all(self, requests):
        total = 0
        async for r in requests:
            total += r["x"]
        return {"sum": total}

    @grpc.bidi_streaming
    async def echo(self, requests):
        async for r in requests:
            yield {"echo": r["x"]}


def make_cluster(seed=1):
    rt = ms.Runtime(seed=seed)
    state = {}

    async def setup():
        h = rt.handle
        state["server"] = h.create_node().name("server").ip("10.0.0.1").init(
            lambda: grpc.Server().add_service(Greeter()).serve("10.0.0.1:50051")
        ).build()
        state["client"] = h.create_node().name("client").ip("10.0.0.2").build()
        await ms.time.sleep(0.1)

    return rt, state, setup


def test_all_four_rpc_shapes():
    rt, state, setup = make_cluster()

    async def main():
        await setup()

        async def run():
            channel = await grpc.connect("http://10.0.0.1:50051")
            stub = grpc.client_for(Greeter, channel)
            r1 = await stub.say_hello({"name": "world"})
            assert r1 == {"message": "Hello world!"}
            frames = await (await stub.count({"n": 4})).collect()
            assert frames == [{"i": 0}, {"i": 1}, {"i": 2}, {"i": 3}]
            r3 = await stub.sum_all([{"x": i} for i in range(5)])
            assert r3 == {"sum": 10}
            out = await (await stub.echo([{"x": "a"}, {"x": "b"}])).collect()
            assert out == [{"echo": "a"}, {"echo": "b"}]
            return True

        return await state["client"].spawn(run())

    assert rt.block_on(main())


def test_unknown_rpc_unimplemented():
    rt, state, setup = make_cluster()

    class Unknown(grpc.Service):
        SERVICE_NAME = "nope.Nope"

        @grpc.unary
        async def nothing(self, request):
            return None

    async def main():
        await setup()

        async def run():
            channel = await grpc.connect("http://10.0.0.1:50051")
            stub = grpc.client_for(Unknown, channel)
            with pytest.raises(grpc.Status) as e:
                await stub.nothing({})
            assert e.value.code == grpc.Code.UNIMPLEMENTED
            return True

        return await state["client"].spawn(run())

    assert rt.block_on(main())


def test_status_propagation_and_internal():
    rt, state, setup = make_cluster()

    async def main():
        await setup()

        async def run():
            channel = await grpc.connect("http://10.0.0.1:50051")
            stub = grpc.client_for(Greeter, channel)
            with pytest.raises(grpc.Status) as e:
                await stub.fail_not_found({})
            assert e.value.code == grpc.Code.NOT_FOUND
            with pytest.raises(grpc.Status) as e:
                await stub.crash_handler({})
            assert e.value.code == grpc.Code.INTERNAL
            assert "handler bug" in e.value.message
            return True

        return await state["client"].spawn(run())

    assert rt.block_on(main())


def test_timeout_deadline_exceeded():
    rt, state, setup = make_cluster()

    async def main():
        await setup()

        async def run():
            channel = await grpc.connect("http://10.0.0.1:50051")
            stub = grpc.client_for(Greeter, channel)
            with pytest.raises(grpc.Status) as e:
                await stub.slow({}, timeout=1.0)
            assert e.value.code == grpc.Code.DEADLINE_EXCEEDED
            # channel-level default timeout
            channel.default_timeout = 0.5
            with pytest.raises(grpc.Status) as e:
                await stub.slow({})
            assert e.value.code == grpc.Code.DEADLINE_EXCEEDED
            return True

        return await state["client"].spawn(run())

    assert rt.block_on(main())


def test_interceptor_metadata():
    rt, state, setup = make_cluster()

    async def main():
        await setup()

        async def run():
            def auth(msg, metadata):
                metadata["user"] = "alice"

            channel = await grpc.connect("http://10.0.0.1:50051", interceptor=auth)
            stub = grpc.client_for(Greeter, channel)
            assert await stub.whoami({}) == {"user": "alice"}

            def reject(msg, metadata):
                raise grpc.Status.permission_denied("nope")

            channel2 = await grpc.connect("http://10.0.0.1:50051", interceptor=reject)
            stub2 = grpc.client_for(Greeter, channel2)
            with pytest.raises(grpc.Status) as e:
                await stub2.whoami({})
            assert e.value.code == grpc.Code.PERMISSION_DENIED
            return True

        return await state["client"].spawn(run())

    assert rt.block_on(main())


def test_concurrent_requests_keep_own_metadata():
    # Two in-flight RPCs whose handlers read metadata only after awaits:
    # each must see its own request's metadata, not the other's (metadata is
    # per-request/per-task, never a module global).
    rt, state, setup = make_cluster()

    async def main():
        await setup()

        async def run():
            async def one_call(user, delay):
                def auth(msg, metadata, user=user):
                    metadata["user"] = user

                channel = await grpc.connect("http://10.0.0.1:50051", interceptor=auth)
                stub = grpc.client_for(Greeter, channel)
                return await stub.slow_whoami({"delay": delay})

            t1 = ms.spawn(one_call("alice", 0.8))
            t2 = ms.spawn(one_call("bob", 0.3))
            r1, r2 = await t1, await t2
            assert r1 == {"user": "alice"}
            assert r2 == {"user": "bob"}
            return True

        return await state["client"].spawn(run())

    assert rt.block_on(main())


def test_connect_refused_when_no_server():
    rt = ms.Runtime(seed=1)

    async def main():
        h = rt.handle
        h.create_node().name("server").ip("10.0.0.1").build()  # nothing bound
        client = h.create_node().name("client").ip("10.0.0.2").build()

        async def run():
            with pytest.raises(grpc.Status) as e:
                await grpc.connect("http://10.0.0.1:50051")
            assert e.value.code == grpc.Code.UNAVAILABLE
            return True

        return await client.spawn(run())

    assert rt.block_on(main())


def test_server_crash_mid_stream_then_restart():
    # reference tonic-example/tests/test.rs:234-278 (server_crash)
    rt, state, setup = make_cluster(seed=3)

    async def main():
        await setup()
        h = rt.handle

        async def run():
            channel = await grpc.connect("http://10.0.0.1:50051")
            stub = grpc.client_for(Greeter, channel)
            stream = await stub.count({"n": 100})
            got = [await stream.__anext__()]
            h.kill(state["server"].id)
            with pytest.raises(grpc.Status) as e:
                while True:
                    got.append(await stream.__anext__())
            assert e.value.code == grpc.Code.UNAVAILABLE
            assert len(got) >= 1

            # calls while down: unavailable
            with pytest.raises(grpc.Status) as e2:
                await stub.say_hello({"name": "x"})
            assert e2.value.code == grpc.Code.UNAVAILABLE

            # restart re-runs init => server comes back
            h.restart(state["server"].id)
            await ms.time.sleep(0.2)
            r = await stub.say_hello({"name": "back"})
            assert r == {"message": "Hello back!"}
            return True

        return await state["client"].spawn(run())

    assert rt.block_on(main())


def test_client_crash_mid_stream_server_survives():
    # reference tonic-example/tests/test.rs:155-202 (client_crash)
    rt, state, setup = make_cluster(seed=5)

    async def main():
        await setup()
        h = rt.handle

        async def doomed_client():
            channel = await grpc.connect("http://10.0.0.1:50051")
            stub = grpc.client_for(Greeter, channel)
            stream = await stub.count({"n": 1000})
            async for _ in stream:
                pass

        state["client"].spawn(doomed_client())
        await ms.time.sleep(0.3)
        h.kill(state["client"].id)
        await ms.time.sleep(0.5)

        # server is still healthy: a fresh client works
        probe = h.create_node().name("probe").ip("10.0.0.9").build()

        async def check():
            channel = await grpc.connect("http://10.0.0.1:50051")
            stub = grpc.client_for(Greeter, channel)
            return await stub.say_hello({"name": "probe"})

        assert (await probe.spawn(check())) == {"message": "Hello probe!"}
        return True

    assert rt.block_on(main())


def test_grpc_deterministic():
    def run(seed):
        import examples.greeter  # noqa: F401  (import works)
        rt, state, setup = make_cluster(seed=seed)
        trace = []

        async def main():
            await setup()

            async def run_c():
                channel = await grpc.connect("http://10.0.0.1:50051")
                stub = grpc.client_for(Greeter, channel)
                for i in range(5):
                    await stub.say_hello({"name": str(i)})
                    trace.append(ms.time.current().now_ns())

            await state["client"].spawn(run_c())

        rt.block_on(main())
        return trace

    assert run(11) == run(11)
    assert run(11) != run(12)
