"""Native executor core: bit-parity with the pure-Python implementations.

The C++ extension is optional; these tests skip when it isn't built
(`python setup_native.py build_ext --inplace`).
"""

import pytest

from madsim_tpu import native

pytestmark = pytest.mark.skipif(not native.AVAILABLE, reason="native core not built")


def test_rng_stream_parity():
    from madsim_tpu.core.rng import Xoshiro256PP

    for seed in (0, 1, 42, 2**64 - 1):
        c = native.Rng(seed=seed)
        p = Xoshiro256PP(seed)
        assert all(c.next_u64() == p.next_u64() for _ in range(5000))


def test_rng_randrange_parity():
    from madsim_tpu.core.rng import GlobalRng

    c = native.Rng(seed=7)
    g = GlobalRng(7)
    g._rng = __import__("madsim_tpu.core.rng", fromlist=["Xoshiro256PP"]).Xoshiro256PP(7)
    for n in (1, 2, 3, 7, 64, 2**32, 10**12):
        for _ in range(200):
            assert c.randrange(n) == g.randrange(n)


def test_timer_ordering_and_cancel():
    t = native.Timer()
    fired = []
    t.add(100, lambda: fired.append("a"))
    b = t.add(50, lambda: fired.append("b"))
    t.add(50, lambda: fired.append("b2"))
    t.add(200, lambda: fired.append("c"))
    t.cancel(b)
    assert t.next_deadline() == 50
    while (cb := t.expire_next(150)) is not None:
        cb()
    assert fired == ["b2", "a"]
    assert t.next_deadline() == 200
    assert len(t) == 1
    # cancelling a stale handle after its slot was recycled must be a no-op
    d = t.add(300, lambda: fired.append("d"))
    t.cancel(b)  # b already fired/cancelled; slot may be reused by d
    assert len(t) == 2  # d and c both still live


def test_queue_pop_random_matches_python_swap_pop():
    # same RNG state + same algorithm => same pop order as the Python queue
    from madsim_tpu.core.rng import GlobalRng

    q = native.Queue()
    for x in range(20):
        q.push(x)
    rng_c = native.Rng(seed=3)

    py_list = list(range(20))
    g = GlobalRng(3)
    from madsim_tpu.core.rng import Xoshiro256PP

    g._rng = Xoshiro256PP(3)

    order_c, order_p = [], []
    for _ in range(20):
        order_c.append(q.pop_random(rng_c))
        n = len(py_list)
        i = g.randrange(n)
        py_list[i], py_list[n - 1] = py_list[n - 1], py_list[i]
        order_p.append(py_list.pop())
    assert order_c == order_p


def test_full_sim_native_matches_pure_python(monkeypatch):
    """The same seed gives the same execution with and without the C++ core."""
    import madsim_tpu as ms
    from madsim_tpu.core import rng as rng_mod, task as task_mod, vtime as vtime_mod

    def run_trace():
        rt = ms.Runtime(seed=11)
        trace = []

        async def worker(tag):
            for _ in range(5):
                await ms.time.sleep(ms.rand())
                trace.append((tag, ms.time.current().now_ns()))

        async def main():
            hs = [ms.spawn(worker(i)) for i in range(4)]
            for h in hs:
                await h

        rt.block_on(main())
        return trace

    native_trace = run_trace()

    import madsim_tpu.native as nat

    monkeypatch.setattr(nat, "AVAILABLE", False)
    pure_trace = run_trace()
    assert native_trace == pure_trace


def test_determinism_check_works_with_native():
    import madsim_tpu as ms

    async def main():
        for _ in range(10):
            await ms.time.sleep(ms.rand())
            ms.randrange(100)

    ms.check_determinism(9, main)


def test_shm_ring_native_python_parity():
    """The native shm data plane (shm_try_write/shm_read) is byte- and
    protocol-compatible with the pure-Python ring: same segment layout,
    same flow control, same rejection behavior — either side of a
    connection may run without the extension."""
    import struct

    from madsim_tpu.real import shm as shm_mod

    def drive(use_native):
        # monkey the module-level fast-path hooks
        saved = (shm_mod._shm_try_write, shm_mod._shm_read)
        if not use_native:
            shm_mod._shm_try_write = shm_mod._shm_read = None
        try:
            ring = shm_mod.ShmRing.create(size=64)
            log = []
            try:
                reader = shm_mod.ShmRing.attach(ring.name)
                # fill, wrap, flow control
                for payload in (b"alpha", b"0" * 40, b"beta" * 5, b"x" * 64):
                    got = ring.try_write(payload)
                    log.append(got)
                    if got is not None:
                        off, ln = got
                        body = reader.read(off, ln)
                        assert body == payload
                        log.append(body)
                # over-capacity write rejected
                log.append(ring.try_write(b"y" * 65))
                # a bad descriptor raises
                try:
                    reader.read(5, 4)
                    log.append("no-error")
                except ValueError:
                    log.append("rejected")
                log.append(struct.unpack("<Q", bytes(ring._shm.buf[:8]))[0])
                reader.close()
            finally:
                ring.close()
            return log
        finally:
            shm_mod._shm_try_write, shm_mod._shm_read = saved

    py = drive(use_native=False)
    if shm_mod._shm_try_write is None:
        pytest.skip("native core not built")  # skipif guard covers this
    nat = drive(use_native=True)
    assert py == nat, (py, nat)
