"""Native executor core: bit-parity with the pure-Python implementations.

The C++ extension is optional; these tests skip when it isn't built
(`python setup_native.py build_ext --inplace`).
"""

import pytest

from madsim_tpu import native

pytestmark = pytest.mark.skipif(not native.AVAILABLE, reason="native core not built")


def test_rng_stream_parity():
    from madsim_tpu.core.rng import Xoshiro256PP

    for seed in (0, 1, 42, 2**64 - 1):
        c = native.Rng(seed=seed)
        p = Xoshiro256PP(seed)
        assert all(c.next_u64() == p.next_u64() for _ in range(5000))


def test_rng_randrange_parity():
    from madsim_tpu.core.rng import GlobalRng

    c = native.Rng(seed=7)
    g = GlobalRng(7)
    g._rng = __import__("madsim_tpu.core.rng", fromlist=["Xoshiro256PP"]).Xoshiro256PP(7)
    for n in (1, 2, 3, 7, 64, 2**32, 10**12):
        for _ in range(200):
            assert c.randrange(n) == g.randrange(n)


def test_timer_ordering_and_cancel():
    t = native.Timer()
    fired = []
    t.add(100, lambda: fired.append("a"))
    b = t.add(50, lambda: fired.append("b"))
    t.add(50, lambda: fired.append("b2"))
    t.add(200, lambda: fired.append("c"))
    t.cancel(b)
    assert t.next_deadline() == 50
    while (cb := t.expire_next(150)) is not None:
        cb()
    assert fired == ["b2", "a"]
    assert t.next_deadline() == 200
    assert len(t) == 1
    # cancelling a stale handle after its slot was recycled must be a no-op
    d = t.add(300, lambda: fired.append("d"))
    t.cancel(b)  # b already fired/cancelled; slot may be reused by d
    assert len(t) == 2  # d and c both still live


def test_queue_pop_random_matches_python_swap_pop():
    # same RNG state + same algorithm => same pop order as the Python queue
    from madsim_tpu.core.rng import GlobalRng

    q = native.Queue()
    for x in range(20):
        q.push(x)
    rng_c = native.Rng(seed=3)

    py_list = list(range(20))
    g = GlobalRng(3)
    from madsim_tpu.core.rng import Xoshiro256PP

    g._rng = Xoshiro256PP(3)

    order_c, order_p = [], []
    for _ in range(20):
        order_c.append(q.pop_random(rng_c))
        n = len(py_list)
        i = g.randrange(n)
        py_list[i], py_list[n - 1] = py_list[n - 1], py_list[i]
        order_p.append(py_list.pop())
    assert order_c == order_p


def test_full_sim_native_matches_pure_python(monkeypatch):
    """The same seed gives the same execution with and without the C++ core."""
    import madsim_tpu as ms
    from madsim_tpu.core import rng as rng_mod, task as task_mod, vtime as vtime_mod

    def run_trace():
        rt = ms.Runtime(seed=11)
        trace = []

        async def worker(tag):
            for _ in range(5):
                await ms.time.sleep(ms.rand())
                trace.append((tag, ms.time.current().now_ns()))

        async def main():
            hs = [ms.spawn(worker(i)) for i in range(4)]
            for h in hs:
                await h

        rt.block_on(main())
        return trace

    native_trace = run_trace()

    import madsim_tpu.native as nat

    monkeypatch.setattr(nat, "AVAILABLE", False)
    pure_trace = run_trace()
    assert native_trace == pure_trace


def test_determinism_check_works_with_native():
    import madsim_tpu as ms

    async def main():
        for _ in range(10):
            await ms.time.sleep(ms.rand())
            ms.randrange(100)

    ms.check_determinism(9, main)
