"""Network sim tests — mirrors reference endpoint.rs:363-583, tcp/mod.rs:57-308,
ipvs.rs:108-131, rpc.rs doctests."""

import pytest

import madsim_tpu as ms
from madsim_tpu.net import Endpoint, NetSim, TcpListener, TcpStream, UdpSocket
from madsim_tpu.net import rpc
from madsim_tpu.core.sync import ChannelClosed


def make_rt(seed=1, **net_kwargs):
    cfg = ms.Config()
    for k, v in net_kwargs.items():
        setattr(cfg.net, k, v)
    return ms.Runtime(seed=seed, config=cfg)


def test_endpoint_send_recv():
    rt = make_rt()

    async def main():
        h = rt.handle
        node1 = h.create_node().name("n1").ip("10.0.0.1").build()
        node2 = h.create_node().name("n2").ip("10.0.0.2").build()

        async def server():
            ep = await Endpoint.bind("10.0.0.1:5000")
            data, frm = await ep.recv_from(7)
            assert data == b"ping"
            await ep.send_to(frm, 8, b"pong")

        async def client():
            ep = await Endpoint.bind("10.0.0.2:0")
            await ep.send_to("10.0.0.1:5000", 7, b"ping")
            data, frm = await ep.recv_from(8)
            assert data == b"pong"
            assert frm == ("10.0.0.1", 5000)
            return True

        node1.spawn(server())
        hc = node2.spawn(client())
        await ms.time.sleep(0.5)
        return await hc

    assert rt.block_on(main())


def test_tag_matching_out_of_order():
    rt = make_rt()

    async def main():
        h = rt.handle
        n1 = h.create_node().ip("10.0.0.1").build()
        n2 = h.create_node().ip("10.0.0.2").build()

        async def sender():
            ep = await Endpoint.bind("10.0.0.1:1000")
            await ep.send_to("10.0.0.2:1000", 1, b"one")
            await ep.send_to("10.0.0.2:1000", 2, b"two")

        got = {}

        async def receiver():
            ep = await Endpoint.bind("10.0.0.2:1000")
            # receive tag 2 first even though tag 1 was sent first
            data2, _ = await ep.recv_from(2)
            data1, _ = await ep.recv_from(1)
            got["two"], got["one"] = data2, data1

        n1.spawn(sender())
        hr = n2.spawn(receiver())
        await hr
        assert got == {"one": b"one", "two": b"two"}

    rt.block_on(main())


def test_rpc_call():
    rt = make_rt()

    @rpc.rpc_request
    class Add:
        def __init__(self, a, b):
            self.a, self.b = a, b

    async def main():
        h = rt.handle
        srv = h.create_node().ip("10.0.0.1").build()
        cli = h.create_node().ip("10.0.0.2").build()

        async def server():
            ep = await Endpoint.bind("10.0.0.1:9000")

            async def handle(req):
                return req.a + req.b

            rpc.add_rpc_handler(ep, Add, handle)

        async def client():
            ep = await Endpoint.bind("10.0.0.2:0")
            return await rpc.call(ep, "10.0.0.1:9000", Add(20, 22))

        srv.spawn(server())
        await ms.time.sleep(0.1)
        return await cli.spawn(client())

    assert rt.block_on(main()) == 42


def test_rpc_with_data():
    rt = make_rt()

    @rpc.rpc_request
    class Echo:
        pass

    async def main():
        h = rt.handle
        srv = h.create_node().ip("10.0.0.1").build()
        cli = h.create_node().ip("10.0.0.2").build()

        async def server():
            ep = await Endpoint.bind("10.0.0.1:9000")

            async def handle(req, data):
                return "ok", data[::-1]

            rpc.add_rpc_handler_with_data(ep, Echo, handle)

        async def client():
            ep = await Endpoint.bind("10.0.0.2:0")
            return await rpc.call_with_data(ep, "10.0.0.1:9000", Echo(), b"abc")

        srv.spawn(server())
        await ms.time.sleep(0.1)
        return await cli.spawn(client())

    assert rt.block_on(main()) == ("ok", b"cba")


def test_rpc_timeout_prunes_mailbox():
    # A timed-out rpc call must not park its late response in the mailbox
    # forever (memory leak on long lossy fuzz runs); the one-shot response tag
    # is forgotten and the late arrival is dropped.
    rt = make_rt()

    @rpc.rpc_request
    class Slow:
        pass

    async def main():
        h = rt.handle
        srv = h.create_node().ip("10.0.0.1").build()
        cli = h.create_node().ip("10.0.0.2").build()

        async def server():
            ep = await Endpoint.bind("10.0.0.1:9000")

            async def handle(req):
                await ms.time.sleep(5.0)  # longer than the caller's timeout
                return "late"

            rpc.add_rpc_handler(ep, Slow, handle)

        async def client():
            ep = await Endpoint.bind("10.0.0.2:0")
            with pytest.raises(ms.time.Elapsed):
                await rpc.call_timeout(ep, "10.0.0.1:9000", Slow(), 1.0)
            # let the late response arrive, then check nothing parked
            await ms.time.sleep(10.0)
            mailbox = ep._socket.mailbox
            assert mailbox.msgs == []
            assert mailbox.registered == []
            return True

        srv.spawn(server())
        await ms.time.sleep(0.1)
        return await cli.spawn(client())

    assert rt.block_on(main())


def test_packet_loss_datagrams_dropped():
    rt = make_rt(seed=3, packet_loss_rate=1.0)

    async def main():
        h = rt.handle
        n1 = h.create_node().ip("10.0.0.1").build()
        n2 = h.create_node().ip("10.0.0.2").build()

        async def sender():
            ep = await Endpoint.bind("10.0.0.1:1000")
            await ep.send_to("10.0.0.2:1000", 0, b"x")

        got = []

        async def receiver():
            ep = await Endpoint.bind("10.0.0.2:1000")
            data, _ = await ep.recv_from(0)
            got.append(data)

        n1.spawn(sender())
        n2.spawn(receiver())
        await ms.time.sleep(5.0)
        return got

    assert rt.block_on(main()) == []


def test_clog_unclog_node():
    rt = make_rt(seed=2)

    async def main():
        h = rt.handle
        n1 = h.create_node().ip("10.0.0.1").build()
        n2 = h.create_node().ip("10.0.0.2").build()
        net = ms.plugin.simulator(NetSim)

        got = []

        async def receiver():
            ep = await Endpoint.bind("10.0.0.2:1000")
            while True:
                data, _ = await ep.recv_from(0)
                got.append((data, round(ms.time.current().elapsed(), 1)))

        async def sender():
            ep = await Endpoint.bind("10.0.0.1:1000")
            await ep.send_to("10.0.0.2:1000", 0, b"a")  # delivered
            await ms.time.sleep(1.0)
            net.clog_node(n2.id)
            await ep.send_to("10.0.0.2:1000", 0, b"b")  # dropped (datagram)
            await ms.time.sleep(1.0)
            net.unclog_node(n2.id)
            await ep.send_to("10.0.0.2:1000", 0, b"c")  # delivered

        n2.spawn(receiver())
        n1.spawn(sender())
        await ms.time.sleep(5.0)
        return got

    got = rt.block_on(main())
    assert [g[0] for g in got] == [b"a", b"c"]


def test_tcp_roundtrip():
    rt = make_rt()

    async def main():
        h = rt.handle
        srv = h.create_node().ip("10.0.0.1").build()
        cli = h.create_node().ip("10.0.0.2").build()

        async def server():
            lis = await TcpListener.bind("10.0.0.1:2000")
            stream, peer = await lis.accept()
            data = await stream.read_exact(5)
            await stream.write_all(data.upper())

        async def client():
            stream = await TcpStream.connect("10.0.0.1:2000")
            await stream.write_all(b"hello")
            return await stream.read_exact(5)

        srv.spawn(server())
        await ms.time.sleep(0.1)
        return await cli.spawn(client())

    assert rt.block_on(main()) == b"HELLO"


def test_tcp_connection_refused():
    rt = make_rt()

    async def main():
        h = rt.handle
        h.create_node().ip("10.0.0.1").build()
        cli = h.create_node().ip("10.0.0.2").build()

        async def client():
            with pytest.raises(ConnectionRefusedError):
                await TcpStream.connect("10.0.0.1:2000")  # nothing bound
            return True

        return await cli.spawn(client())

    assert rt.block_on(main())


def test_tcp_survives_clog_with_backoff():
    # reference tcp/mod.rs: clog mid-connection, data arrives after unclog
    rt = make_rt(seed=4)

    async def main():
        h = rt.handle
        srv = h.create_node().ip("10.0.0.1").build()
        cli = h.create_node().ip("10.0.0.2").build()
        net = ms.plugin.simulator(NetSim)

        async def server():
            lis = await TcpListener.bind("10.0.0.1:2000")
            stream, _ = await lis.accept()
            return await stream.read_exact(4)

        hs = srv.spawn(server())
        await ms.time.sleep(0.1)

        async def client():
            stream = await TcpStream.connect("10.0.0.1:2000")
            net.clog_node(srv.id)
            await stream.write_all(b"data")  # sent while clogged
            await ms.time.sleep(3.0)
            net.unclog_node(srv.id)

        cli.spawn(client())
        t0 = ms.time.current().elapsed()
        data = await hs
        took = ms.time.current().elapsed() - t0
        assert data == b"data"
        assert took >= 3.0  # had to wait out the clog

    rt.block_on(main())


def test_tcp_eof_on_peer_close():
    rt = make_rt()

    async def main():
        h = rt.handle
        srv = h.create_node().ip("10.0.0.1").build()
        cli = h.create_node().ip("10.0.0.2").build()

        async def server():
            lis = await TcpListener.bind("10.0.0.1:2000")
            stream, _ = await lis.accept()
            stream.close()

        async def client():
            stream = await TcpStream.connect("10.0.0.1:2000")
            return await stream.read()

        srv.spawn(server())
        await ms.time.sleep(0.1)
        return await cli.spawn(client())

    assert rt.block_on(main()) == b""


def test_kill_node_closes_connections():
    rt = make_rt()

    async def main():
        h = rt.handle
        srv = h.create_node().ip("10.0.0.1").build()
        cli = h.create_node().ip("10.0.0.2").build()

        async def server():
            lis = await TcpListener.bind("10.0.0.1:2000")
            while True:
                stream, _ = await lis.accept()

        async def client():
            stream = await TcpStream.connect("10.0.0.1:2000")
            await ms.time.sleep(1.0)
            rt.handle.kill(srv.id)
            # peer killed => EOF
            return await stream.read()

        srv.spawn(server())
        await ms.time.sleep(0.1)
        return await cli.spawn(client())

    assert rt.block_on(main()) == b""


def test_udp_socket():
    rt = make_rt()

    async def main():
        h = rt.handle
        n1 = h.create_node().ip("10.0.0.1").build()
        n2 = h.create_node().ip("10.0.0.2").build()

        async def a():
            sock = await UdpSocket.bind("10.0.0.1:3000")
            data, frm = await sock.recv_from()
            await sock.send_to(data + b"!", frm)

        async def b():
            sock = await UdpSocket.bind("10.0.0.2:3000")
            await sock.send_to(b"hi", "10.0.0.1:3000")
            data, _ = await sock.recv_from()
            return data

        n1.spawn(a())
        await ms.time.sleep(0.1)
        return await n2.spawn(b())

    assert rt.block_on(main()) == b"hi!"


def test_dns_lookup():
    rt = make_rt()

    async def main():
        h = rt.handle
        srv = h.create_node().ip("10.0.0.1").build()
        cli = h.create_node().ip("10.0.0.2").build()
        net = ms.plugin.simulator(NetSim)
        net.add_dns_record("server.example.com", "10.0.0.1")

        async def server():
            ep = await Endpoint.bind("10.0.0.1:5000")
            data, frm = await ep.recv_from(0)
            await ep.send_to(frm, 1, data)

        async def client():
            ep = await Endpoint.bind("10.0.0.2:0")
            await ep.send_to("server.example.com:5000", 0, b"dns works")
            data, _ = await ep.recv_from(1)
            return data

        srv.spawn(server())
        await ms.time.sleep(0.1)
        return await cli.spawn(client())

    assert rt.block_on(main()) == b"dns works"


def test_ipvs_round_robin():
    rt = make_rt()

    async def main():
        h = rt.handle
        backends = [
            h.create_node().ip(f"10.0.0.{i}").build() for i in (1, 2)
        ]
        cli = h.create_node().ip("10.0.0.9").build()
        net = ms.plugin.simulator(NetSim)
        net.ipvs.add_service(("10.1.0.1", 80, "udp"))
        net.ipvs.add_server(("10.1.0.1", 80, "udp"), "10.0.0.1:80")
        net.ipvs.add_server(("10.1.0.1", 80, "udp"), "10.0.0.2:80")

        hits = {1: 0, 2: 0}

        def backend(i):
            async def run():
                ep = await Endpoint.bind(f"10.0.0.{i}:80")
                while True:
                    await ep.recv_from(0)
                    hits[i] += 1

            return run

        for i, b in zip((1, 2), backends):
            b.spawn(backend(i)())

        async def client():
            ep = await Endpoint.bind("10.0.0.9:0")
            for _ in range(6):
                await ep.send_to("10.1.0.1:80", 0, b"req")
                await ms.time.sleep(0.1)

        cli.spawn(client())
        await ms.time.sleep(3.0)
        return hits

    hits = rt.block_on(main())
    assert hits == {1: 3, 2: 3}


def test_rpc_hooks_drop_requests():
    rt = make_rt()

    async def main():
        h = rt.handle
        n1 = h.create_node().ip("10.0.0.1").build()
        n2 = h.create_node().ip("10.0.0.2").build()
        net = ms.plugin.simulator(NetSim)

        got = []

        async def receiver():
            ep = await Endpoint.bind("10.0.0.2:1000")
            while True:
                data, _ = await ep.recv_from(0)
                got.append(data)

        async def sender():
            ep = await Endpoint.bind("10.0.0.1:1000")
            net.hook_rpc_req(n1.id, lambda msg: msg[1] != b"drop-me")
            await ep.send_to("10.0.0.2:1000", 0, b"keep")
            await ep.send_to("10.0.0.2:1000", 0, b"drop-me")
            await ep.send_to("10.0.0.2:1000", 0, b"keep2")

        n2.spawn(receiver())
        n1.spawn(sender())
        await ms.time.sleep(2.0)
        return got

    assert rt.block_on(main()) == [b"keep", b"keep2"]


def test_net_stat_counts_messages():
    rt = make_rt()

    async def main():
        h = rt.handle
        n1 = h.create_node().ip("10.0.0.1").build()
        n2 = h.create_node().ip("10.0.0.2").build()
        net = ms.plugin.simulator(NetSim)

        async def sender():
            ep = await Endpoint.bind("10.0.0.1:1000")
            for _ in range(5):
                await ep.send_to("10.0.0.2:1000", 0, b"x")

        async def receiver():
            ep = await Endpoint.bind("10.0.0.2:1000")
            while True:
                await ep.recv_from(0)

        n2.spawn(receiver())
        n1.spawn(sender())
        await ms.time.sleep(1.0)
        return net.stat().msg_count

    assert rt.block_on(main()) == 5


def test_addr_in_use():
    rt = make_rt()

    async def main():
        h = rt.handle
        n1 = h.create_node().ip("10.0.0.1").build()

        async def run():
            await Endpoint.bind("10.0.0.1:5000")
            with pytest.raises(OSError, match="address already in use"):
                await Endpoint.bind("10.0.0.1:5000")
            return True

        return await n1.spawn(run())

    assert rt.block_on(main())


def test_deterministic_network_trace():
    def run(seed):
        rt = make_rt(seed=seed, packet_loss_rate=0.3)
        events = []

        async def main():
            h = rt.handle
            n1 = h.create_node().ip("10.0.0.1").build()
            n2 = h.create_node().ip("10.0.0.2").build()

            async def receiver():
                ep = await Endpoint.bind("10.0.0.2:1000")
                while True:
                    data, _ = await ep.recv_from(0)
                    events.append((data, ms.time.current().now_ns()))

            async def sender():
                ep = await Endpoint.bind("10.0.0.1:1000")
                for i in range(20):
                    await ep.send_to("10.0.0.2:1000", 0, str(i).encode())
                    await ms.time.sleep(0.05)

            n2.spawn(receiver())
            n1.spawn(sender())
            await ms.time.sleep(5.0)

        rt.block_on(main())
        return events

    a, b, c = run(11), run(11), run(12)
    assert a == b
    assert a != c
    assert 0 < len(a) < 20  # some dropped, some delivered


def test_kill_waiter_does_not_lose_channel_message():
    # regression: a killed task parked on Channel.recv must not swallow wakeups
    rt = make_rt()
    from madsim_tpu.core.sync import Channel

    async def main():
        h = rt.handle
        n1 = h.create_node().build()
        n2 = h.create_node().build()
        chan = Channel()
        got = []

        async def receiver(tag):
            v = await chan.recv()
            got.append((tag, v))

        n1.spawn(receiver("dead"))
        n2.spawn(receiver("alive"))
        await ms.time.sleep(0.1)
        h.kill(n1.id)
        await ms.time.sleep(0.1)
        chan.send_nowait("hello")
        await ms.time.sleep(0.1)
        return got

    assert rt.block_on(main()) == [("alive", "hello")]


def test_auto_ip_skips_user_assigned():
    rt = make_rt()

    async def main():
        h = rt.handle
        h.create_node().ip("192.168.0.2").build()  # node 1 takes node 2's auto IP
        n2 = h.create_node().build()  # must not crash
        net = ms.plugin.simulator(NetSim)
        assert net.get_ip(n2.id) not in (None, "192.168.0.2")

    rt.block_on(main())


def test_write_to_killed_peer_raises_broken_pipe():
    rt = make_rt()

    async def main():
        h = rt.handle
        srv = h.create_node().ip("10.0.0.1").build()
        cli = h.create_node().ip("10.0.0.2").build()

        async def server():
            lis = await TcpListener.bind("10.0.0.1:2000")
            while True:
                await lis.accept()

        async def client():
            stream = await TcpStream.connect("10.0.0.1:2000")
            await ms.time.sleep(0.5)
            rt.handle.kill(srv.id)
            with pytest.raises(BrokenPipeError):
                for _ in range(3):
                    await stream.write_all(b"x")
                    await ms.time.sleep(0.1)
            return True

        srv.spawn(server())
        await ms.time.sleep(0.1)
        return await cli.spawn(client())

    assert rt.block_on(main())


def test_tcp_connect_releases_ephemeral_port():
    rt = make_rt()

    async def main():
        h = rt.handle
        srv = h.create_node().ip("10.0.0.1").build()
        cli = h.create_node().ip("10.0.0.2").build()

        async def server():
            lis = await TcpListener.bind("10.0.0.1:2000")
            while True:
                stream, _ = await lis.accept()
                stream.close()

        async def client():
            from madsim_tpu.net.netsim import NetSim as NS

            net = ms.plugin.simulator(NS)
            for _ in range(50):
                stream = await TcpStream.connect("10.0.0.1:2000")
                stream.close()
            # all ephemeral binds released
            return len(net.network.nodes[cli.id].sockets)

        srv.spawn(server())
        await ms.time.sleep(0.1)
        return await cli.spawn(client())

    assert rt.block_on(main()) == 0
