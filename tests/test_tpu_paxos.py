"""Single-decree Paxos (tpu/paxos.py): the fourth device protocol — and
the authoring guide's 'a fourth protocol is an afternoon' claim, tested.
House pattern: safety under the full chaos battery with a PROGRESS
assertion, determinism, injected-bug detection, crafted-state units."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu.tpu import BatchedSim, SimConfig, summarize
from madsim_tpu.tpu.batch import run_batch
from madsim_tpu.tpu.paxos import make_paxos_spec, paxos_workload


def test_paxos_decides_and_agrees_quiet():
    sim = BatchedSim(
        make_paxos_spec(5), SimConfig(horizon_us=3_000_000, msg_depth_msg=2,
                                      msg_spare_slots=2)
    )
    state = sim.run(jnp.arange(32), max_steps=20_000)
    s = summarize(state, sim.spec)
    assert s["violations"] == 0
    # progress: consensus actually reached everywhere on a quiet network
    assert s["all_decided_lanes"] == 32, s
    assert s["total_overflow"] == 0


def test_paxos_safe_under_full_chaos_battery():
    wl = paxos_workload(virtual_secs=8.0)
    result = run_batch(range(256), wl, repro_on_host=False, max_traces=0)
    assert result.violations == 0
    s = result.summary
    # dueling proposers + loss + crashes + partitions: most lanes still
    # reach full agreement within the horizon, and nothing overflowed
    assert s["all_decided_lanes"] > 200, s
    assert s["total_overflow"] == 0, s


def test_paxos_determinism():
    wl = paxos_workload(virtual_secs=3.0)
    sim = BatchedSim(wl.spec, wl.config)
    a = sim.run(jnp.arange(16), max_steps=20_000)
    b = sim.run(jnp.arange(16), max_steps=20_000)
    assert np.array_equal(np.asarray(a.node.decided), np.asarray(b.node.decided))
    assert np.array_equal(np.asarray(a.events), np.asarray(b.events))


@pytest.mark.deep
def test_paxos_injected_bug_caught():
    """The canonical Paxos mistake: phase 2 ignores the discovered
    accepted value and pushes the proposer's own. Chaos interleaves two
    ballots' quorums and two different values get chosen — agreement
    violated, caught by the invariant."""
    wl = paxos_workload(virtual_secs=10.0)
    buggy = dataclasses.replace(
        wl, spec=make_paxos_spec(5, buggy_ignore_discovered=True)
    )
    result = run_batch(range(1024), buggy, repro_on_host=False, max_traces=1)
    assert result.violations > 0, result.summary
    # control under identical chaos
    clean = run_batch(range(1024), wl, repro_on_host=False, max_traces=0)
    assert clean.violations == 0, clean.summary


def test_paxos_crafted_agreement_states():
    spec = make_paxos_spec(3)
    import jax

    node, _t = jax.vmap(
        jax.vmap(spec.init, in_axes=(0, 0)), in_axes=(0, None)
    )(jnp.zeros((1, 3), jnp.uint32), jnp.arange(3, dtype=jnp.int32))
    one = jax.tree_util.tree_map(lambda x: x[0], node)
    alive = jnp.ones((3,), jnp.bool_)
    ok = lambda n: bool(spec.check_invariants(n, alive, jnp.int32(0)))

    assert ok(one)  # nothing decided
    agree = one._replace(decided=one.decided.at[0].set(7).at[2].set(7))
    assert ok(agree)  # partial agreement fine
    split = one._replace(decided=one.decided.at[0].set(7).at[2].set(9))
    assert not ok(split)  # two values chosen => violation
