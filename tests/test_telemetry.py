"""Telemetry (madsim_tpu/telemetry): the observe-only contract, pinned.

The subsystem's promises (docs/observability.md):
  * **observe-only, bit-exact**: explorer fingerprints and the canonical
    golden trajectory digest are IDENTICAL with telemetry enabled vs
    disabled — capture happens at decode/host boundaries, never inside
    jitted code;
  * **one schema**: every event on the JSONL sink validates against
    ``madsim-tpu-telemetry/1`` and round-trips; the nemesis per-occurrence
    rows serialize in stable key/row order (docs/nemesis.md);
  * **timelines are faithful**: the Perfetto export of a violating replay
    matches the `format_trace` text event-for-event (every TraceEvent has
    exactly one anchor track/flow/instant event), and is well-formed
    Chrome-trace JSON;
  * **the farm is scrapeable**: `campaign serve` maintains status.json +
    a Prometheus textfile atomically — a concurrent reader never sees a
    torn file;
  * **near-free**: the span-wrapped dispatch loop costs <2% over bare
    (bench.bench_telemetry_overhead).

`make telemetry-smoke` runs this WHOLE file (including the slow-marked
bit-identity/repro/overhead tests, which the tier-1 wall budget keeps out
of the default `-m 'not slow'` run).
"""

import dataclasses
import json
import os
import threading
import time

import numpy as np
import pytest

import madsim_tpu.telemetry as telemetry

from tests.test_triage import _sched_workload


@pytest.fixture(autouse=True)
def _telemetry_reset():
    """Telemetry state is process-global: never leak an enable."""
    telemetry.disable()
    yield
    telemetry.disable()


# ------------------------------------------------------------ event schema


def test_event_schema_roundtrip(tmp_path):
    """Every sink line validates against madsim-tpu-telemetry/1 and
    round-trips through JSON unchanged."""
    reg = telemetry.enable(out_dir=str(tmp_path))
    reg.counter("sweep_violations", "v").inc(3, workload="raft")
    reg.gauge("sweep_occupancy", "o").set(0.97, device=0)
    reg.histogram("span_seconds").observe(0.02, site="dispatch")
    with telemetry.span("dispatch", site="test"):
        pass
    telemetry.disable()

    path = tmp_path / "events.jsonl"
    events = telemetry.read_events(str(path))  # parse_event on every line
    assert [e["kind"] for e in events] == [
        "counter", "gauge", "histogram", "histogram", "span",
    ]
    # seq is a gapless monotone cursor
    assert [e["seq"] for e in events] == list(range(len(events)))
    for e in events:
        assert e["format"] == telemetry.TELEMETRY_FORMAT
        # byte-level round trip: parse(dump(parse(line))) is identity
        assert telemetry.parse_event(json.dumps(e)) == e
    c = events[0]
    assert (c["name"], c["value"], c["labels"]) == (
        "sweep_violations", 3, {"workload": "raft"},
    )
    sp = events[-1]
    assert sp["labels"] == {"site": "test"} and sp["dur_s"] >= 0


def test_event_schema_rejects_malformed():
    ok = {
        "format": telemetry.TELEMETRY_FORMAT, "kind": "counter",
        "name": "x", "value": 1, "labels": {}, "seq": 0,
    }
    telemetry.parse_event(json.dumps(ok))
    for breakage in (
        {"format": "bogus/9"},
        {"kind": "summary"},
        {"value": None, "kind": "span"},  # span needs t0_s/dur_s
        {"labels": [1, 2]},
    ):
        bad = {**ok, **breakage}
        with pytest.raises(ValueError):
            telemetry.parse_event(json.dumps(bad))
    with pytest.raises(ValueError):
        telemetry.parse_event("[1, 2]")


def test_registry_prom_exposition():
    reg = telemetry.MetricsRegistry()
    reg.counter("sweep_violations", "violations").inc(2, workload="raft")
    reg.counter("sweep_violations").inc(1, workload="kv")
    reg.gauge("farm_queue_depth").set(4)
    reg.histogram("span_seconds", buckets=(0.1, 1.0)).observe(0.5)
    text = reg.to_prom()
    assert 'madsim_sweep_violations_total{workload="raft"} 2' in text
    assert 'madsim_sweep_violations_total{workload="kv"} 1' in text
    assert "madsim_farm_queue_depth 4" in text
    assert 'madsim_span_seconds_bucket{le="0.1"} 0' in text
    assert 'madsim_span_seconds_bucket{le="1.0"} 1' in text
    assert 'madsim_span_seconds_bucket{le="+Inf"} 1' in text
    assert "madsim_span_seconds_count 1" in text
    # same name, different kind: loud error, never a silent shadow
    with pytest.raises(TypeError):
        reg.gauge("sweep_violations")
    # user-supplied label values (campaign ids) are exposition-escaped —
    # one hostile id must not poison the whole scrape
    reg.gauge("farm_campaign_generation").set(1, campaign='a"b\\c\nd')
    assert 'campaign="a\\"b\\\\c\\nd"' in reg.to_prom()


# -------------------------------------------- nemesis occurrence-row schema


def test_chaos_occurrence_rows_stable_schema_roundtrip():
    """The per-occurrence fire rows the telemetry sink serializes
    (docs/nemesis.md "Occurrence rows"): key order clause,k,lanes; row
    order = OCC_CLAUSES registry order then ascending k — stable however
    the summary dict was ordered — and a JSON round trip is identity."""
    from madsim_tpu.nemesis import OCC_CLAUSES

    summary = {  # deliberately scrambled insertion order
        "occfires_spike_k0": 7,
        "occfires_crash_k2": 1,
        "occfires_partition_k1": 2,
        "occfires_crash_k0": 3,
        "fires_crash": 4,  # clause totals are NOT occurrence rows
    }
    rows = telemetry.chaos_rows(summary)
    assert rows == [
        {"clause": "crash", "k": 0, "lanes": 3},
        {"clause": "crash", "k": 2, "lanes": 1},
        {"clause": "partition", "k": 1, "lanes": 2},
        {"clause": "spike", "k": 0, "lanes": 7},
    ]
    # row order follows the OCC_CLAUSES registry, not string sort luck
    clauses = [r["clause"] for r in rows]
    assert clauses == sorted(
        clauses, key=lambda c: OCC_CLAUSES.index(c)
    )
    # key order inside each row is part of the schema (json preserves it)
    for r in rows:
        assert list(r) == ["clause", "k", "lanes"]
    assert json.loads(json.dumps(rows)) == rows
    assert telemetry.chaos_rows({}) == []


def test_chaos_rows_carry_disk_occurrences_end_to_end():
    """The r18 durability clause in the occurrence-row schema: `disk`
    rows sort after the older clauses (OCC_CLAUSES registry order), and a
    real wal run's summary emits exactly one row per fired disk episode —
    the k set equals the lane's occ_fired bitmask."""
    summary = {
        "occfires_disk_k1": 5,
        "occfires_crash_k0": 1,
        "occfires_disk_k0": 9,
    }
    assert telemetry.chaos_rows(summary) == [
        {"clause": "crash", "k": 0, "lanes": 1},
        {"clause": "disk", "k": 0, "lanes": 9},
        {"clause": "disk", "k": 1, "lanes": 5},
    ]

    import jax.numpy as jnp
    import numpy as np

    from madsim_tpu.nemesis import OCC_ROW
    from madsim_tpu.tpu import BatchedSim, summarize
    from madsim_tpu.tpu.wal import wal_workload

    wl = wal_workload(virtual_secs=3.0)
    sim = BatchedSim(wl.spec, wl.config)
    st = sim.run(jnp.asarray([5], jnp.uint32), max_steps=40_000)
    s = summarize(st)
    mask = int(np.asarray(st.occ_fired)[0, OCC_ROW["disk"]])
    ks = {k for k in range(32) if (mask >> k) & 1}
    assert ks, "the wal workload's DiskFault clause must fire by 3s"
    got = {
        r["k"] for r in telemetry.chaos_rows(s) if r["clause"] == "disk"
    }
    assert got == ks
    # the clause's three fire kinds ride the totals vocabulary too
    assert s.get("fires_disk_slow", 0) >= 1
    assert s.get("fires_disk_crash", 0) >= 1


# ------------------------------------------------------------ lint satellite


def test_telemetry_module_passes_entropy_lint_without_pragmas():
    """telemetry.py uses only `time.perf_counter` (allowlisted monotonic
    clock): the ambient-entropy rule passes with ZERO violations and the
    module carries no `# madsim: allow` pragma."""
    from madsim_tpu.analysis.lint import check_entropy_file, repo_root

    root = repo_root()
    path = os.path.join(root, "madsim_tpu", "telemetry.py")
    res = check_entropy_file(path, root)
    assert res.violations == [], res.violations
    assert res.checked > 0  # the rule actually scanned call sites
    with open(path) as f:
        src = f.read()
    assert "madsim: allow" not in src
    assert "perf_counter" in src  # the allowlisted clock is what it uses


# ------------------------------------------------------------------ spans


def test_span_is_noop_singleton_when_disabled():
    a, b = telemetry.span("x"), telemetry.span("y", q=1)
    assert a is b  # no per-call allocation on the disabled path
    with a:
        pass
    telemetry.enable()
    assert telemetry.span("x") is not telemetry.span("x")
    telemetry.disable()
    assert telemetry.spans() == []


def test_spans_capture_threads_and_export_wellformed_perfetto(tmp_path):
    telemetry.enable()

    def worker():
        with telemetry.span("slice", campaign="c1", device=1):
            time.sleep(0.002)

    with telemetry.span("dispatch", off=0):
        time.sleep(0.001)
    t = threading.Thread(target=worker, name="lane-1")
    t.start()
    t.join()
    recs = telemetry.spans()
    assert sorted(r.name for r in recs) == ["dispatch", "slice"]
    assert {r.thread for r in recs} == {"MainThread", "lane-1"}
    assert all(r.dur_s > 0 and r.t0_s >= 0 for r in recs)
    # the registry histogram sees every span, labeled by site
    h = telemetry.get_registry().histogram("span_seconds")
    assert h.snapshot(site="dispatch")["count"] == 1
    assert h.snapshot(site="slice")["count"] == 1

    path = str(tmp_path / "loop.perfetto.json")
    telemetry.write_spans_perfetto(path)
    telemetry.disable()
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    slices = [e for e in evs if e["ph"] == "X"]
    assert len(slices) == 2
    for e in evs:
        assert {"ph", "pid", "ts"} <= set(e)
        if e["ph"] == "X":
            assert e["dur"] > 0 and "tid" in e and e["name"]
    # one wall-clock track per thread
    threads = [
        e["args"]["name"] for e in evs
        if e["ph"] == "M" and e["name"] == "thread_name"
    ]
    assert sorted(threads) == ["MainThread", "lane-1"]


def test_host_runtime_metrics_route_through_registry():
    """The host half of the sweep vocabulary: RuntimeMetrics censuses,
    occupancy, dispatch rounds and loop wall route through the same
    registry (and export flat via to_telemetry)."""
    import madsim_tpu as ms

    rt = ms.Runtime(seed=1)

    async def body():
        async def forever():
            while True:
                await ms.time.sleep(1.0)

        node = ms.Handle.current().create_node().name("n").build()
        node.spawn(forever())
        await ms.time.sleep(2.0)

    rt.block_on(body())
    m = rt.handle.metrics()
    flat = m.to_telemetry()
    assert flat["host_nodes"] == 2  # main + n
    assert flat["host_dispatches"] > 0 and flat["host_device_ms"] >= 0
    assert 0 < flat["host_occupancy"] <= 1
    assert json.loads(json.dumps(flat)) == flat

    reg = telemetry.enable()
    telemetry.record_runtime_metrics(m, runtime="rt1")
    telemetry.disable()
    assert reg.gauge("host_nodes").value(runtime="rt1") == 2
    assert reg.counter("host_dispatches").value(runtime="rt1") == \
        flat["host_dispatches"]
    assert reg.gauge("host_occupancy").value(runtime="rt1") == \
        m.occupancy


# ----------------------------------------------- bit-identity (acceptance)


@pytest.mark.chaos
def test_explorer_fingerprint_bit_identical_telemetry_on_off(tmp_path):
    """The hard constraint, verified not promised: the SAME search with
    telemetry fully on (registry + JSONL sink + spans) fingerprints
    bit-identically to the bare run, and the sink actually captured the
    explorer's generation stats while doing so."""
    from madsim_tpu.explore import Explorer

    from tests.test_explore import _planted_workload

    wl = _planted_workload()
    off = Explorer(
        wl, meta_seed=11, lanes=16, chunk=8, shrink_violations=False,
    ).run(2)

    telemetry.enable(out_dir=str(tmp_path))
    on = Explorer(
        wl, meta_seed=11, lanes=16, chunk=8, shrink_violations=False,
    ).run(2)
    reg = telemetry.get_registry()
    assert reg.gauge("explore_generations").value(meta_seed=11) == 2
    assert reg.gauge("explore_coverage_bits").value(meta_seed=11) == \
        on.coverage_bits
    telemetry.disable()

    assert on.fingerprint() == off.fingerprint()
    assert on.coverage_curve == off.coverage_curve
    assert on.corpus_digest == off.corpus_digest
    # and the stream it produced validates line by line
    events = telemetry.read_events(str(tmp_path / "events.jsonl"))
    assert any(e["name"] == "explore_coverage_bits" for e in events)
    assert any(e["kind"] == "span" for e in events)


@pytest.mark.slow
@pytest.mark.chaos
def test_golden_digest_bit_identical_with_telemetry_on():
    """The canonical raft golden trajectory digest (pinned in
    tests/test_state_layout.py) is reproduced exactly with telemetry
    enabled — the engine's device programs are untouched by capture."""
    from tests import test_state_layout as tsl

    telemetry.enable()
    try:
        tsl._golden_one("raft")  # asserts canonical_digest == GOLDEN
    finally:
        telemetry.disable()


# ----------------------------------------- virtual-time Perfetto timelines


@pytest.fixture(scope="module")
def violating_sweep(tmp_path_factory):
    """One planted-bug sweep with telemetry on, shared by the timeline and
    metrics tests: 24 seeds of the deposed-leader re-stamp workload, one
    violating seed traced (and its timeline auto-written)."""
    from madsim_tpu.tpu.batch import run_batch

    tdir = str(tmp_path_factory.mktemp("telem-sweep"))
    wl = _sched_workload()
    telemetry.enable(out_dir=tdir)
    try:
        result = run_batch(
            range(24), wl, repro_on_host=False, max_traces=1,
        )
    finally:
        telemetry.disable()
    assert result.violations > 0, result.summary
    return wl, result, tdir


def _timeline_anchors(doc):
    """Anchor events (the 1:1 TraceEvent images): deliveries are X slices
    with cat=deliver, everything else instants."""
    return [
        e for e in doc["traceEvents"]
        if (e["ph"] == "X" and e.get("cat") == "deliver") or e["ph"] == "i"
    ]


@pytest.mark.chaos
def test_perfetto_timeline_matches_format_trace_event_for_event(
    violating_sweep,
):
    """Acceptance: the Perfetto file of a violating raft replay carries
    the same information as the format_trace text — every TraceEvent has
    exactly one anchor (track slice or instant) at its virtual time, every
    delivery one src→dst flow pair, and the JSON is well-formed
    Chrome-trace (pid/tid/ts/ph on every event)."""
    from madsim_tpu.tpu.trace import format_trace

    wl, result, _ = violating_sweep
    seed, events = next(iter(result.traces.items()))
    assert any(e.kind == "violation" for e in events)
    text_lines = format_trace(events).splitlines()
    assert len(text_lines) == len(events)

    doc = telemetry.perfetto_from_events(
        events, n_nodes=wl.spec.n_nodes, label=f"raft seed {seed}"
    )
    doc = json.loads(json.dumps(doc))  # what a file reader would see
    assert doc["otherData"]["format"] == telemetry.TELEMETRY_FORMAT

    # -- well-formed chrome trace: required fields on every event --------
    for e in doc["traceEvents"]:
        assert {"ph", "pid", "ts"} <= set(e), e
        if e["ph"] != "M":
            assert "tid" in e, e
        if e["ph"] == "X":
            assert e["dur"] >= 1
        if e["ph"] == "i":
            assert e["s"] in ("t", "p", "g")

    # -- event-for-event: one anchor per TraceEvent ----------------------
    anchors = _timeline_anchors(doc)
    assert len(anchors) == len(events)
    pool = list(anchors)

    def take(pred, te):
        for i, e in enumerate(pool):
            if pred(e):
                return pool.pop(i)
        raise AssertionError(f"no timeline anchor for {te}")

    for te in events:
        if te.kind == "deliver":
            name = te.msg_name or f"kind{te.msg_kind}"
            a = take(
                lambda e: e["ph"] == "X" and e.get("cat") == "deliver"
                and e["ts"] == te.t_us and e["tid"] == te.node
                and e["name"] == name
                and e["args"]["src"] == te.src
                and e["args"]["payload"] == list(te.payload or ()),
                te,
            )
            assert a["args"]["step"] == te.step
        elif te.kind == "timer":
            take(
                lambda e: e["ph"] == "i" and e.get("cat") == "timer"
                and e["ts"] == te.t_us and e["tid"] == te.node, te,
            )
        elif te.kind in ("violation", "deadlock"):
            take(
                lambda e: e["ph"] == "i" and e.get("cat") == "invariant"
                and e["name"] == te.kind and e["ts"] == te.t_us, te,
            )
        else:
            take(
                lambda e: e["ph"] == "i" and e.get("cat") == "chaos"
                and e["ts"] == te.t_us
                and e["name"].split(" ")[0] == te.kind, te,
            )
    assert pool == []  # nothing fabricated either

    # -- deliveries flow src→dst: one s/f pair per delivery, ids 1:1 -----
    delivers = [e for e in events if e.kind == "deliver"]
    starts = [e for e in doc["traceEvents"] if e["ph"] == "s"]
    ends = [e for e in doc["traceEvents"] if e["ph"] == "f"]
    assert len(starts) == len(ends) == len(delivers)
    by_id = {e["id"]: e for e in starts}
    assert len(by_id) == len(starts)  # unique flow ids
    src_dst = sorted((e.src, e.node, e.t_us) for e in delivers)
    flow_pairs = sorted(
        (by_id[f["id"]]["tid"], f["tid"], f["ts"]) for f in ends
    )
    assert flow_pairs == src_dst

    # -- the violation is visible as a process-scoped marker -------------
    v = [
        e for e in doc["traceEvents"]
        if e.get("cat") == "invariant" and e["name"] == "violation"
    ]
    assert len(v) == 1 and v[0]["s"] == "p"

    # node tracks are declared for every node
    names = {
        e["args"]["name"] for e in doc["traceEvents"] if e["ph"] == "M"
    }
    assert {f"node{n}" for n in range(wl.spec.n_nodes)} <= names


def test_perfetto_flow_pairing_uses_lineage_edges():
    """The r12 flow-pairing fix: with TWO same-kind messages in flight on
    ONE link, only the lineage `sent_eid` edges can draw the right
    arrows — any (src, dst, kind) matching (and the old fall-back of
    anchoring at the delivery instant) ties them. The regression: two
    deliveries node0->node1 of the same kind, sent at t=100 and t=200,
    delivered OUT OF ORDER (reorder window) at t=1300 and t=1250 — the
    arrow of the t=1300 delivery must start at t=100, the t=1250 one at
    t=200."""
    from madsim_tpu.tpu.trace import TraceEvent

    events = [
        TraceEvent(step=1, t_us=100, kind="timer", node=0, eid=1, lam=1),
        TraceEvent(step=2, t_us=200, kind="timer", node=0, eid=2, lam=2),
        # second send overtakes the first (same src, dst, kind!)
        TraceEvent(step=5, t_us=1250, kind="deliver", node=1, src=0,
                   msg_kind=3, msg_name="PING", eid=3, sent_eid=2, lam=4),
        TraceEvent(step=6, t_us=1300, kind="deliver", node=1, src=0,
                   msg_kind=3, msg_name="PING", eid=4, sent_eid=1, lam=6),
    ]
    doc = telemetry.perfetto_from_events(events, n_nodes=2)
    starts = {e["id"]: e for e in doc["traceEvents"] if e["ph"] == "s"}
    ends = {e["id"]: e for e in doc["traceEvents"] if e["ph"] == "f"}
    assert len(starts) == len(ends) == 2
    arrow_of = {ends[i]["ts"]: starts[i]["ts"] for i in ends}
    assert arrow_of == {1250: 200, 1300: 100}, (
        "flow arrows must follow the sent_eid edges, not delivery order"
    )
    # delivery anchors expose the edge for tooltip-level debugging
    xs = [e for e in doc["traceEvents"]
          if e["ph"] == "X" and e.get("cat") == "deliver"]
    assert sorted((x["args"]["eid"], x["args"]["sent_eid"]) for x in xs) \
        == [(3, 2), (4, 1)]
    # legacy traces (no lineage) keep the old fallback: arrows anchored
    # at the delivery instant, never a wrong-origin guess
    legacy = [dataclasses.replace(e, eid=-1, sent_eid=-1) for e in events]
    doc2 = telemetry.perfetto_from_events(legacy, n_nodes=2)
    for s in (e for e in doc2["traceEvents"] if e["ph"] == "s"):
        assert s["ts"] in (1250, 1300)


def test_perfetto_lineage_flow_on_real_trace():
    """End to end on a real lineage-enabled traced replay: every flow
    arrow starts at its send event's time on the source track, strictly
    before (or at) the delivery it feeds."""
    from madsim_tpu.tpu import make_raft_spec
    from madsim_tpu.tpu.engine import BatchedSim
    from madsim_tpu.tpu.trace import extract_trace

    spec = make_raft_spec()
    sim = BatchedSim(spec, None, lineage=True)
    _, recs = sim.run_traced(3, max_steps=250)
    events = extract_trace(recs, kind_names=spec.msg_kind_names)
    by_eid = {e.eid: e for e in events if e.eid >= 0}
    doc = telemetry.perfetto_from_events(events, n_nodes=spec.n_nodes)
    starts = {e["id"]: e for e in doc["traceEvents"] if e["ph"] == "s"}
    ends = {e["id"]: e for e in doc["traceEvents"] if e["ph"] == "f"}
    delivers = [e for e in events if e.kind == "deliver"]
    assert delivers and len(starts) == len(delivers)
    checked = 0
    for i, f in ends.items():
        s = starts[i]
        assert s["ts"] <= f["ts"]
        # the arrow's start is a real send event's (track, time)
        d = next(
            e for e in delivers
            if e.t_us == f["ts"] and e.node == f["tid"]
        )
        send = by_eid[d.sent_eid]
        assert (s["tid"], s["ts"]) == (send.node, send.t_us)
        checked += 1
    assert checked == len(delivers)


@pytest.mark.chaos
def test_run_batch_routes_metrics_and_writes_timeline(violating_sweep):
    """With telemetry enabled, run_batch emits the sweep's summary through
    the registry (violations, occupancy, dispatches, device_ms, chaos
    fires per clause AND per occurrence) and drops the traced violation's
    timeline next to the events stream — all post-sweep, observe-only."""
    wl, result, tdir = violating_sweep
    seed = next(iter(result.traces))

    # the auto-written timeline parses and anchors 1:1 with the trace
    tpath = os.path.join(tdir, f"{wl.spec.name}-seed{seed}.perfetto.json")
    assert os.path.exists(tpath)
    with open(tpath) as f:
        doc = json.load(f)
    assert len(_timeline_anchors(doc)) == len(result.traces[seed])

    # the events stream validates and carries the routed summary
    events = telemetry.read_events(os.path.join(tdir, "events.jsonl"))
    by_name = {}
    for e in events:
        by_name.setdefault(e["name"], []).append(e)
    assert sum(
        e["value"] for e in by_name["sweep_violations"]
    ) == result.violations
    assert sum(
        e["value"] for e in by_name["sweep_dispatches"]
    ) == result.dispatches
    assert "sweep_device_ms" in by_name and "sweep_occupancy" in by_name
    # chaos fires per clause and per occurrence rode through
    fire_clauses = {
        e["labels"]["clause"] for e in by_name.get("chaos_fires", [])
    }
    assert {"crash", "partition"} <= fire_clauses
    occ_rows = by_name.get("chaos_occurrence_lanes", [])
    assert occ_rows and all("k" in e["labels"] for e in occ_rows)
    # spans of the pipelined loop are on the stream too
    sites = {
        e["labels"].get("site") for e in events if e["kind"] == "span"
    }
    assert {"run_batch"} <= sites


# ------------------------------------------------------- repro --perfetto


@pytest.mark.slow
@pytest.mark.chaos
def test_repro_trace_perfetto_writes_timeline_next_to_bundle(
    violating_sweep, tmp_path, capsys,
):
    """Satellite: `python -m madsim_tpu.repro bundle.json --trace 5
    --perfetto` replays the bundle, prints the trace tail, and writes the
    timeline next to the bundle — bundle schema unchanged."""
    from madsim_tpu import repro, triage

    wl, result, _ = violating_sweep
    seed = result.violating_seeds[0]
    sr = triage.shrink_seed(
        wl, seed, out_dir=str(tmp_path),
        spec_ref="tests.test_triage:planted_restamp_spec",
    )
    bundle_doc = json.load(open(sr.bundle_path))

    rc = repro.main([sr.bundle_path, "--trace", "5", "--perfetto"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "device replay OK" in out
    root, _ = os.path.splitext(sr.bundle_path)
    tpath = f"{root}.perfetto.json"
    assert f"perfetto timeline: {tpath}" in out
    with open(tpath) as f:
        doc = json.load(f)
    anchors = _timeline_anchors(doc)
    assert anchors and any(
        e.get("cat") == "invariant" and e["name"] == "violation"
        for e in anchors
    )
    # flag is additive: the bundle on disk is byte-for-byte what the
    # shrinker wrote (schema unchanged)
    assert json.load(open(sr.bundle_path)) == bundle_doc


# ----------------------------------------------------- farm status surface


def _stub_serve(d, requests, **kw):
    """campaign.serve with filesystem-only stub campaigns."""
    from madsim_tpu import campaign
    from tests.test_campaign import _report

    class Stub:
        def __init__(self, cid):
            self.cid, self.generation, self.bugs = cid, 0, []

        def run(self, g):
            self.generation += g
            time.sleep(0.001)  # widen the read/replace race window
            return _report()

        def checkpoint(self):
            os.makedirs(
                os.path.join(d, "campaigns", self.cid), exist_ok=True
            )

    os.makedirs(os.path.join(d, "queue"), exist_ok=True)
    for name, req in requests.items():
        with open(os.path.join(d, "queue", f"{name}.json"), "w") as f:
            json.dump(req, f)
    return campaign.serve(
        d, out=lambda s: None,
        factory=lambda r, cd, rd, log: Stub(r["id"]),
        sleep=lambda s: None, **kw,
    )


def test_serve_status_surface_contents(tmp_path):
    from madsim_tpu import campaign

    d = str(tmp_path / "svc")
    res = _stub_serve(
        d,
        {"a": {"workload": "raft", "generations": 3},
         "b": {"workload": "raft", "generations": 1}},
        max_rounds=10, idle_rounds=1, devices=["devA", "devB"],
    )
    assert res["completed"] == ["b", "a"]
    with open(os.path.join(d, campaign.STATUS)) as f:
        status = json.load(f)
    assert status["format"] == telemetry.FARM_STATUS_FORMAT
    assert status["queue_depth"] == 0 and status["active"] == {}
    assert sorted(status["completed"]) == ["a", "b"]
    assert status["devices"] == 2 and len(status["per_device"]) == 2
    for row in status["per_device"]:
        assert row["busy_s"] > 0 and 0 < row["occupancy"] <= 1
        assert row["seeds_run"] > 0 and row["seeds_per_sec"] > 0
    # the textfile face carries the same numbers, prometheus-shaped
    with open(os.path.join(d, campaign.METRICS_TEXTFILE)) as f:
        prom = f.read()
    assert "madsim_farm_queue_depth 0" in prom
    assert "madsim_farm_completed_campaigns 2" in prom
    assert 'madsim_farm_device_occupancy{device="0"}' in prom
    assert 'madsim_farm_device_seeds_per_sec{device="1"}' in prom
    # mid-flight snapshot shows the live cursors: rerun with a round cap
    d2 = str(tmp_path / "svc2")
    _stub_serve(
        d2, {"c": {"workload": "raft", "generations": 5}},
        max_rounds=2, idle_rounds=1,
    )
    with open(os.path.join(d2, campaign.STATUS)) as f:
        live = json.load(f)
    assert live["active"]["c"]["generation"] == 2
    assert live["active"]["c"]["remaining"] == 3
    # `telemetry render` reads the surface (dir or file)
    assert telemetry.main(["render", d2]) == 0


def test_serve_status_updates_are_atomic(tmp_path):
    """Reader-never-sees-a-torn-file: a thread hammering status.json +
    metrics.prom throughout a many-round serve sees only complete,
    parseable documents (tmp+os.replace), and no tmp litter survives."""
    from madsim_tpu import campaign

    d = str(tmp_path / "svc")
    status_path = os.path.join(d, campaign.STATUS)
    prom_path = os.path.join(d, campaign.METRICS_TEXTFILE)
    stop = threading.Event()
    torn, reads = [], [0]

    def reader():
        while not stop.is_set():
            for path in (status_path, prom_path):
                try:
                    with open(path) as f:
                        text = f.read()
                except FileNotFoundError:
                    continue  # not written yet — fine, never torn
                reads[0] += 1
                try:
                    if path is status_path:
                        doc = json.loads(text)
                        if doc.get("format") != telemetry.FARM_STATUS_FORMAT:
                            torn.append(f"missing format: {text[:80]!r}")
                    elif text and not text.endswith("\n"):
                        torn.append(f"truncated textfile: {text[-40:]!r}")
                except json.JSONDecodeError as e:
                    torn.append(f"{e}: {text[:80]!r}")

    t = threading.Thread(target=reader, name="scraper")
    t.start()
    try:
        _stub_serve(
            d, {"a": {"workload": "raft", "generations": 40}},
            max_rounds=40, idle_rounds=1,
        )
    finally:
        stop.set()
        t.join()
    assert torn == [], torn[:5]
    assert reads[0] > 10  # the reader genuinely raced the writer
    assert not [p for p in os.listdir(d) if ".tmp" in p]


# ------------------------------------------------------------------- CLI


def test_cli_tail_and_render(tmp_path, capsys):
    reg = telemetry.enable(out_dir=str(tmp_path))
    reg.counter("sweep_violations").inc(2, workload="raft")
    with telemetry.span("dispatch"):
        pass
    telemetry.disable()
    events_path = str(tmp_path / "events.jsonl")

    assert telemetry.main(["tail", events_path, "-n", "10"]) == 0
    out = capsys.readouterr().out
    assert "sweep_violations{workload=raft} = 2" in out
    assert "span dispatch" in out

    # --validate catches corrupt lines
    with open(events_path, "a") as f:
        f.write('{"format": "nope"}\n')
    assert telemetry.main(
        ["tail", events_path, "--validate"]
    ) == 1
    capsys.readouterr()

    # render recognizes a timeline document too
    tl = str(tmp_path / "t.json")
    telemetry.write_perfetto(tl, [])
    assert telemetry.main(["render", tl]) == 0
    assert "chrome-trace" in capsys.readouterr().out
    assert telemetry.main(["render", str(tmp_path / "missing.json")]) == 1


# ------------------------------------------------------- overhead budget


@pytest.mark.slow
@pytest.mark.chaos
def test_telemetry_overhead_under_2pct():
    """The bench's telemetry_overhead key on the smoke workload: the
    span-wrapped dispatch loop costs <2% over bare (min-of-repeats damps
    scheduler noise; the per-span µs cost is reported alongside). The
    true span cost is ~10µs x 8 spans on a ~0.4s loop (0.02%); one
    re-measure absorbs the rare CI scheduler spike that dwarfs it."""
    import bench

    r = bench.bench_telemetry_overhead(
        lanes=128, virtual_secs=0.3, iters=4, repeats=6
    )
    if r["overhead_pct"] >= 2.0:  # pragma: no cover - noise retry
        r = bench.bench_telemetry_overhead(
            lanes=128, virtual_secs=0.3, iters=4, repeats=6
        )
    assert r["overhead_pct"] < 2.0, r
    # sanity on the budget arithmetic: µs-scale spans on ms-scale
    # dispatches — the analytic bound agrees with the measured one
    analytic_pct = (
        r["spans_per_dispatch"] * r["span_us"] * r["dispatches"]
        / (r["bare_s"] * 1e6) * 100
    )
    assert analytic_pct < 2.0, r
