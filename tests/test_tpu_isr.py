"""Kafka-family ISR replication (the sixth device protocol) — the house
test pattern from docs/authoring_protocol_specs.md: safety under the
chaos battery, determinism, the planted canonical bug caught (on BOTH
faces, and only under the chaos class that exposes it — membership
churn), and host-twin wiring."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu.tpu import BatchedSim, isr_workload, make_isr_spec, summarize
from madsim_tpu.workloads import isr_host


def test_isr_safety_under_chaos_battery():
    wl = isr_workload(virtual_secs=5.0)
    sim = BatchedSim(wl.spec, wl.config)
    state = sim.run(jnp.arange(256), max_steps=30_000)
    s = summarize(state, wl.spec)
    assert s["violations"] == 0
    assert s["total_overflow"] == 0
    # progress: the high watermark advances and the ISR stays populated
    # (a frozen fuzz proves nothing)
    assert s["mean_hw"] > 5
    assert s["mean_isr_size"] >= 1


def test_isr_determinism():
    wl = isr_workload(virtual_secs=2.0)
    sim = BatchedSim(wl.spec, wl.config)
    a = sim.run(jnp.arange(32), max_steps=10_000)
    b = sim.run(jnp.arange(32), max_steps=10_000)
    for x, y in zip(
        __import__("jax").tree_util.tree_leaves(a.node),
        __import__("jax").tree_util.tree_leaves(b.node),
    ):
        assert (np.asarray(x) == np.asarray(y)).all()


def test_stale_isr_bug_caught_only_under_membership_churn():
    """The canonical planted bug: a leader that re-admits a fetching
    replica to the ISR without the catch-up check. Only membership churn
    (remove -> down past repl_timeout -> fresh join) regresses a
    replica's acked offset below the high watermark — the chaos class
    the reconfig clause exists for."""
    wl = isr_workload(virtual_secs=6.0)
    buggy = make_isr_spec(5, buggy_stale_isr=True)

    # without churn (loss only): eviction needs ~6 consecutive losses,
    # and an evicted-but-durable replica rarely falls behind hw — the
    # bug hides
    quiet_cfg = dataclasses.replace(
        wl.config,
        crash_interval_lo_us=0, crash_interval_hi_us=0,
        nem_reconfig_interval_lo_us=0, nem_reconfig_interval_hi_us=0,
    )
    state = BatchedSim(buggy, quiet_cfg).run(jnp.arange(128), max_steps=40_000)
    quiet = summarize(state)["violations"]

    # reconfig churn alone (no crash clause) makes it near-certain
    churn_cfg = dataclasses.replace(
        wl.config, crash_interval_lo_us=0, crash_interval_hi_us=0
    )
    state = BatchedSim(buggy, churn_cfg).run(jnp.arange(128), max_steps=40_000)
    with_churn = summarize(state)["violations"]
    assert with_churn > quiet
    assert with_churn > 64

    # control: the correct catch-up spec is clean under identical churn
    state = BatchedSim(wl.spec, churn_cfg).run(jnp.arange(128), max_steps=40_000)
    assert summarize(state)["violations"] == 0


def test_isr_host_twin_clean_and_bug_on_both_faces():
    r = isr_host.fuzz_one_seed(1, virtual_secs=6.0)
    assert r["hw"] > 0 and r["isr_size"] >= 1

    # host face: pinned violating seed (found by sweeping 0..11 — all hit)
    with pytest.raises(isr_host.InvariantViolation):
        isr_host.fuzz_one_seed(1, virtual_secs=10.0, buggy=True)
    # the correct protocol is clean under the SAME chaos and seed
    isr_host.fuzz_one_seed(1, virtual_secs=10.0)

    # workload wiring: host_repro present and runs end to end
    out = isr_workload(virtual_secs=4.0).host_repro(5)
    assert out["violations"] == 0
