"""Core runtime semantics tests.

Mirrors the reference's inline test intent: scheduler semantics
(task/mod.rs:771-1072), virtual time (time/mod.rs:227-266), determinism
(rand.rs:265-308), random-scheduling divergence (task/mod.rs:948-972).
"""

import pytest

import madsim_tpu as ms
from madsim_tpu.core import context
from madsim_tpu.core.task import DeadlockError, TimeLimitError


def test_block_on_returns_value():
    rt = ms.Runtime(seed=1)

    async def main():
        return 42

    assert rt.block_on(main()) == 42


def test_spawn_and_join():
    rt = ms.Runtime(seed=1)

    async def child(x):
        await ms.time.sleep(0.5)
        return x * 2

    async def main():
        h = ms.spawn(child(21))
        return await h

    assert rt.block_on(main()) == 42


def test_sleep_advances_virtual_time():
    rt = ms.Runtime(seed=1)

    async def main():
        t = ms.time.current()
        start = t.elapsed()
        await ms.time.sleep(30.0)
        return t.elapsed() - start

    took = rt.block_on(main())
    assert 30.0 <= took < 30.1


def test_sleep_ordering():
    rt = ms.Runtime(seed=7)
    order = []

    async def sleeper(tag, dur):
        await ms.time.sleep(dur)
        order.append(tag)

    async def main():
        hs = [
            ms.spawn(sleeper("c", 3.0)),
            ms.spawn(sleeper("a", 1.0)),
            ms.spawn(sleeper("b", 2.0)),
        ]
        for h in hs:
            await h

    rt.block_on(main())
    assert order == ["a", "b", "c"]


def test_deadlock_panics():
    rt = ms.Runtime(seed=1)

    async def main():
        await ms.Future()  # never completes

    with pytest.raises(DeadlockError):
        rt.block_on(main())


def test_time_limit():
    rt = ms.Runtime(seed=1)
    rt.set_time_limit(10.0)

    async def main():
        await ms.time.sleep(100.0)

    with pytest.raises(TimeLimitError):
        rt.block_on(main())


def test_timeout_elapsed_and_ok():
    rt = ms.Runtime(seed=1)

    async def slow():
        await ms.time.sleep(10.0)
        return "late"

    async def fast():
        await ms.time.sleep(0.1)
        return "fast"

    async def main():
        with pytest.raises(TimeoutError):
            await ms.time.timeout(1.0, slow())
        return await ms.time.timeout(1.0, fast())

    assert rt.block_on(main()) == "fast"


def test_kill_drops_tasks():
    rt = ms.Runtime(seed=1)
    state = {"ticks": 0}

    async def ticker():
        while True:
            await ms.time.sleep(1.0)
            state["ticks"] += 1

    async def main():
        node = rt.handle.create_node().name("n1").build()
        node.spawn(ticker())
        await ms.time.sleep(5.5)
        rt.handle.kill(node.id)
        seen = state["ticks"]
        await ms.time.sleep(5.0)
        assert state["ticks"] == seen  # no more ticks after kill
        assert rt.handle.is_exit(node.id)
        return seen

    assert rt.block_on(main()) == 5


def test_restart_reruns_init():
    rt = ms.Runtime(seed=1)
    starts = []

    async def server_main():
        starts.append(ms.time.current().elapsed())
        while True:
            await ms.time.sleep(1.0)

    async def main():
        node = rt.handle.create_node().name("srv").init(server_main).build()
        await ms.time.sleep(2.0)
        rt.handle.restart(node.id)
        await ms.time.sleep(2.0)
        return len(starts)

    assert rt.block_on(main()) == 2


def test_restart_on_panic():
    rt = ms.Runtime(seed=3)
    attempts = []

    async def flaky():
        attempts.append(ms.time.current().elapsed())
        if len(attempts) < 3:
            raise RuntimeError("boom")
        # stay alive once stable
        while True:
            await ms.time.sleep(1.0)

    async def main():
        rt.handle.create_node().name("flaky").init(flaky).restart_on_panic().build()
        await ms.time.sleep(60.0)
        return len(attempts)

    assert rt.block_on(main()) == 3
    # restarts are delayed 1-10s
    assert attempts[1] - attempts[0] >= 1.0
    assert attempts[2] - attempts[1] >= 1.0


def test_unhandled_panic_propagates():
    rt = ms.Runtime(seed=1)

    async def bad():
        raise ValueError("user bug")

    async def main():
        ms.spawn(bad())
        await ms.time.sleep(1.0)

    with pytest.raises(ValueError, match="user bug"):
        rt.block_on(main())


def test_pause_resume():
    rt = ms.Runtime(seed=1)
    state = {"ticks": 0}

    async def ticker():
        while True:
            await ms.time.sleep(1.0)
            state["ticks"] += 1

    async def main():
        node = rt.handle.create_node().name("n").build()
        node.spawn(ticker())
        await ms.time.sleep(3.5)
        rt.handle.pause(node.id)
        frozen = state["ticks"]
        await ms.time.sleep(10.0)
        assert state["ticks"] == frozen
        rt.handle.resume(node.id)
        await ms.time.sleep(3.0)
        assert state["ticks"] > frozen

    rt.block_on(main())


def test_abort_task():
    rt = ms.Runtime(seed=1)

    async def forever():
        while True:
            await ms.time.sleep(1.0)

    async def main():
        h = ms.spawn(forever())
        await ms.time.sleep(2.5)
        h.abort()
        with pytest.raises(ms.JoinError):
            await h
        assert h.is_finished()

    rt.block_on(main())


def test_ctrl_c_listened():
    rt = ms.Runtime(seed=1)
    got = []

    async def server():
        import madsim_tpu.signal as signal

        await signal.ctrl_c()
        got.append(True)

    async def main():
        node = rt.handle.create_node().name("s").build()
        node.spawn(server())
        await ms.time.sleep(1.0)
        rt.handle.send_ctrl_c(node.id)
        await ms.time.sleep(1.0)
        assert got == [True]
        assert not rt.handle.is_exit(node.id)

    rt.block_on(main())


def test_ctrl_c_unlistened_kills():
    rt = ms.Runtime(seed=1)

    async def main():
        node = rt.handle.create_node().name("s").build()
        await ms.time.sleep(1.0)
        rt.handle.send_ctrl_c(node.id)
        assert rt.handle.is_exit(node.id)

    rt.block_on(main())


def test_same_seed_same_execution():
    def run(seed):
        rt = ms.Runtime(seed=seed)
        trace = []

        async def worker(tag):
            for _ in range(5):
                await ms.time.sleep(ms.rand())
                trace.append((tag, ms.time.current().now_ns()))

        async def main():
            hs = [ms.spawn(worker(i)) for i in range(4)]
            for h in hs:
                await h

        rt.block_on(main())
        return trace

    assert run(42) == run(42)
    assert run(42) != run(43)


def test_seeds_give_distinct_interleavings():
    # reference task/mod.rs:948-972: 10 seeds => 10 distinct orders
    def interleaving(seed):
        rt = ms.Runtime(seed=seed)
        order = []

        async def w(tag):
            for _ in range(3):
                await ms.yield_now()
                order.append(tag)

        async def main():
            hs = [ms.spawn(w(i)) for i in range(4)]
            for h in hs:
                await h

        rt.block_on(main())
        return tuple(order)

    seen = {interleaving(s) for s in range(10)}
    assert len(seen) >= 8  # nearly all distinct


def test_system_time_deterministic_and_around_2022():
    rt = ms.Runtime(seed=5)

    async def main():
        return ms.time.current().now_time()

    t1 = rt.block_on(main())
    t2 = ms.Runtime(seed=5).block_on(main())
    assert t1 == t2
    # between 2021 and 2024
    assert 1.6e9 < t1 < 1.71e9


def test_check_determinism_passes():
    async def main():
        for _ in range(10):
            await ms.time.sleep(ms.rand())
            ms.randrange(100)

    ms.check_determinism(7, main)


def test_check_determinism_catches_nondeterminism():
    import itertools

    counter = itertools.count()

    async def main():
        # depends on global mutable state across runs => nondeterministic
        if next(counter) % 2 == 1:
            ms.rand()

    with pytest.raises(ms.DeterminismError):
        ms.check_determinism(7, main)


def test_metrics():
    rt = ms.Runtime(seed=1)

    async def forever():
        while True:
            await ms.time.sleep(1.0)

    async def main():
        m = rt.handle.metrics()
        node = rt.handle.create_node().name("n").build()
        node.spawn(forever())
        node.spawn(forever())
        await ms.yield_now()
        assert m.num_nodes() == 2
        assert m.num_tasks_of(node.id) == 2
        rt.handle.kill(node.id)
        await ms.time.sleep(1.0)
        assert m.num_tasks_of(node.id) == 0

    rt.block_on(main())


def test_interval():
    rt = ms.Runtime(seed=1)

    async def main():
        t = ms.time.current()
        iv = ms.time.interval(1.0)
        ticks = []
        for _ in range(4):
            await iv.tick()
            ticks.append(round(t.elapsed(), 3))
        return ticks

    ticks = rt.block_on(main())
    assert ticks[0] < 0.001
    assert [round(b - a) for a, b in zip(ticks, ticks[1:])] == [1, 1, 1]


def test_fs_read_write_and_power_fail():
    rt = ms.Runtime(seed=1)
    from madsim_tpu import fs

    async def main():
        f = await fs.File.create("/data/log")
        await f.write_all_at(b"hello", 0)
        await f.sync_all()
        await f.write_all_at(b" world", 5)
        assert await f.read_at(32, 0) == b"hello world"

        sim = ms.plugin.simulator(fs.FsSim)
        node_id = ms.plugin.node()
        sim.power_fail(node_id)
        # unsynced tail lost
        assert await fs.read("/data/log") == b"hello"

    rt.block_on(main())


def test_fs_power_fail_drops_never_synced_files():
    # create -> power_fail -> stat: a file created but NEVER synced has no
    # durable directory entry, so a power loss erases the whole inode —
    # the path must be gone, not present-but-empty (recovery code that
    # stat()s such a file must see what a real disk would show)
    rt = ms.Runtime(seed=1)
    from madsim_tpu import fs

    async def main():
        f = await fs.File.create("/data/wal")
        await f.write_all_at(b"doomed", 0)
        g = await fs.File.create("/data/kept")
        await g.write_all_at(b"ok", 0)
        await g.sync_all()
        await g.write_all_at(b"XX", 0)  # unsynced overwrite on a synced file

        sim = ms.plugin.simulator(fs.FsSim)
        node_id = ms.plugin.node()
        sim.power_fail(node_id)
        assert sim.get_file_size(node_id, "/data/wal") is None
        try:
            await fs.File.open("/data/wal")
            raise AssertionError("never-synced file must not survive")
        except FileNotFoundError:
            pass
        # the synced file survives with its synced content only
        assert await fs.read("/data/kept") == b"ok"

    rt.block_on(main())


def test_fs_power_fail_rolls_back_inplace_overwrites():
    # an unsynced overwrite of an already-synced byte range must NOT survive
    # a power failure (content snapshot, not just length truncation)
    rt = ms.Runtime(seed=1)
    from madsim_tpu import fs

    async def main():
        f = await fs.File.create("/data/log")
        await f.write_all_at(b"aaaaa", 0)
        await f.sync_all()
        await f.write_all_at(b"XX", 1)  # unsynced in-place overwrite
        assert await f.read_at(32, 0) == b"aXXaa"

        sim = ms.plugin.simulator(fs.FsSim)
        sim.power_fail(ms.plugin.node())
        assert await fs.read("/data/log") == b"aaaaa"

    rt.block_on(main())


def test_fs_wipe_node_drops_even_synced_inodes():
    # the membership-JOIN rule next to power_fail's crash rule: a synced
    # file SURVIVES a power failure but does NOT survive wipe_node — a
    # node rejoining after a `reconfig` removal is a different machine,
    # so a create -> sync -> remove -> rejoin -> stat sequence must see
    # an empty disk, not the pre-removal inode (the resurrection bug the
    # r17 regression fixed)
    rt = ms.Runtime(seed=1)
    from madsim_tpu import fs

    async def main():
        f = await fs.File.create("/data/segment")
        await f.write_all_at(b"durable", 0)
        await f.sync_all()

        sim = ms.plugin.simulator(fs.FsSim)
        node_id = ms.plugin.node()
        sim.power_fail(node_id)
        assert await fs.read("/data/segment") == b"durable"  # crash: kept

        sim.wipe_node(node_id)  # membership join: a brand-new replica
        assert sim.get_file_size(node_id, "/data/segment") is None
        try:
            await fs.File.open("/data/segment")
            raise AssertionError("pre-wipe inode resurrected after join")
        except FileNotFoundError:
            pass

    rt.block_on(main())


def test_notify_stores_at_most_one_permit():
    # tokio Notify semantics: N notify_one calls with no waiters grant ONE
    # stored wakeup, not N
    rt = ms.Runtime(seed=1)

    async def main():
        n = ms.sync.Notify()
        n.notify_one()
        n.notify_one()
        n.notify_one()
        await n.notified()  # consumes the single stored permit

        woke = []

        async def waiter():
            await n.notified()
            woke.append(True)

        ms.spawn(waiter())
        await ms.time.sleep(0.1)
        assert woke == []  # no second stored permit
        n.notify_one()
        await ms.time.sleep(0.1)
        assert woke == [True]

    rt.block_on(main())


def test_nested_runtime_forbidden():
    rt = ms.Runtime(seed=1)

    async def main():
        rt2 = ms.Runtime(seed=2)

        async def inner():
            return 1

        rt2.block_on(inner())

    with pytest.raises(RuntimeError, match="within a Runtime"):
        rt.block_on(main())


def test_node_lookup_by_name():
    rt = ms.Runtime(seed=1)

    async def main():
        rt.handle.create_node().name("alpha").build()
        node = rt.handle.get_node("alpha")
        assert node is not None and node.name == "alpha"
        rt.handle.kill("alpha")
        assert rt.handle.is_exit("alpha")

    rt.block_on(main())
