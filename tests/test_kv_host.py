"""The kv host twin (workloads/kv_host.py): same protocol as tpu/kv.py on
the host runtime, verified by the SAME exact oracle (per-key Wing-Gong
linearizability + revision monotonicity) — kv's debuggable second face."""

import pytest

from madsim_tpu.workloads.kv_host import InvariantViolation, fuzz_one_seed


def test_clean_kv_linearizable_under_partitions():
    for seed in (1, 2, 3):
        r = fuzz_one_seed(seed, virtual_secs=5.0, partitions=True)
        assert r["acked_ops"] > 20, r
        assert r["max_epoch"] > 0


def test_determinism_same_seed_same_stats():
    a = fuzz_one_seed(7, virtual_secs=3.0)
    b = fuzz_one_seed(7, virtual_secs=3.0)
    assert a == b


@pytest.mark.deep
def test_buggy_local_reads_caught_by_linearizability():
    """The planted stale-read bug (serve reads locally, no quorum probe)
    must be caught by the host oracle under partitions — the same bug
    class the device face plants and catches (tpu/kv.py
    buggy_local_read_spec)."""
    caught = 0
    for seed in range(12):
        try:
            fuzz_one_seed(seed, virtual_secs=8.0, partitions=True, buggy=True)
        except InvariantViolation:
            caught += 1
    assert caught > 0, "the stale-read bug was never caught in 12 seeds"


@pytest.mark.deep
def test_clean_kv_with_crashes_and_partitions():
    for seed in (11, 12):
        r = fuzz_one_seed(seed, virtual_secs=8.0, chaos=True, partitions=True)
        assert r["acked_ops"] > 10, r
