"""Device face of the r18 durability axis: the WAL spec, its durable
plane (spec.durable_fields watermark + on_recover), and the planted
ack-before-fsync bug's full contrast matrix.

The matrix the clause exists for (docs/nemesis.md "DiskFault"):
  correct spec x disk chaos   -> zero violations (fsync-before-ack holds)
  buggy spec   x quiet disk   -> zero violations (the bug is invisible)
  buggy spec   x disk chaos   -> violations (lost acks surface)
covered here and in tests/test_host_twins.py (host face + 3-face twin).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu.tpu import BatchedSim, summarize
from madsim_tpu.tpu.wal import (
    buggy_ack_before_fsync_spec,
    make_wal_spec,
    wal_workload,
)


def test_wal_spec_declares_the_durability_contract():
    """The spec's durable plane is exactly {nonce, log_len}: the server
    identity and what fsync promised — NOT the volatile fsync
    bookkeeping, NOT client state (a client disk crash conservatively
    rolls to init)."""
    spec = make_wal_spec(4)
    assert spec.durable_fields == ("nonce", "log_len")
    assert spec.sync_field == "syncs"
    assert spec.on_recover is not None


def test_wal_durability_plane_in_carry_partition():
    """The watermark rides the hot carry as `hot.dur.<field>` (one twin
    per durable field) and the loss counter as `cold.unsynced_loss` —
    the shrink/refill machinery and the range certifier see them as
    first-class leaves."""
    from madsim_tpu.tpu.engine import carry_partition

    wl = wal_workload(virtual_secs=2.0)
    sim = BatchedSim(wl.spec, wl.config)
    state = sim._init(np.arange(4, dtype=np.uint32))
    parts = carry_partition(state)
    assert "dur.nonce" in parts["hot"]
    assert "dur.log_len" in parts["hot"]
    assert "unsynced_loss" in parts["cold"]


def test_wal_correct_spec_survives_disk_chaos():
    """fsync-before-ack tolerates the full clause: across 256 seeds of
    slow/dying/torn disks there is not one lost ack — and not one lost
    DURABLE byte either (the counter stays zero because the correct
    server syncs every append before advancing log_len, so the watermark
    never trails; losing nothing unsynced is the correctness argument)."""
    wl = wal_workload(virtual_secs=6.0)
    sim = BatchedSim(wl.spec, wl.config)
    st = sim.run(jnp.arange(256), max_steps=40_000)
    s = summarize(st)
    assert s["violations"] == 0
    assert s["fires_disk_crash"] > 0
    assert int(np.asarray(st.unsynced_loss).sum()) == 0


def test_wal_buggy_flag_only_changes_the_ack_path():
    """The planted spec differs from the correct one ONLY in handlers —
    same layout, same durable plane, same narrow contract — so every
    A/B between them isolates the ack-before-fsync decision."""
    a, b = make_wal_spec(4), buggy_ack_before_fsync_spec(n_nodes=4)
    assert a.durable_fields == b.durable_fields
    assert a.narrow_fields == b.narrow_fields
    assert a.narrow_horizon_us == b.narrow_horizon_us


def test_wal_unsynced_loss_attributes_the_ack_path():
    """The cold counter is the clause's witness, and it separates the
    specs under IDENTICAL chaos: the group-committing buggy server loses
    unsynced durable state at disk crashes (counter positive), the
    fsync-before-ack server has nothing unsynced to lose (zero). Same
    seeds, same schedule — only the ack path differs."""
    loud = wal_workload(virtual_secs=6.0, buggy=True)
    sim_l = BatchedSim(loud.spec, loud.config)
    st_l = sim_l.run(jnp.arange(64), max_steps=40_000)
    assert int(np.asarray(st_l.unsynced_loss).sum()) > 0

    quiet = wal_workload(virtual_secs=6.0, buggy=True, disk=False)
    sim_q = BatchedSim(quiet.spec, quiet.config)
    st_q = sim_q.run(jnp.arange(64), max_steps=40_000)
    assert int(np.asarray(st_q.unsynced_loss).sum()) == 0


@pytest.mark.chaos
def test_wal_planted_bug_fires_and_is_attributable():
    """buggy x disk violates on many lanes, and every violating lane's
    own unsynced_loss is positive — the violation is attributable to a
    durable-state loss on that lane, not cross-lane luck."""
    wl = wal_workload(virtual_secs=8.0, buggy=True)
    sim = BatchedSim(wl.spec, wl.config)
    st = sim.run(jnp.arange(256), max_steps=40_000)
    viol = np.asarray(st.violated)
    assert viol.sum() >= 8, f"only {int(viol.sum())}/256 lanes violated"
    loss = np.asarray(st.unsynced_loss)
    assert (loss[viol != 0] > 0).all()
