"""The differential oracle (madsim_tpu/oracle.py): schedule-matched host
replay as a standing bug detector.

Three pillars, none vacuous:

  * the host NemesisDriver consumes the compiled per-seed schedule
    VERBATIM — all eight clauses, pure schedule == host-applied stream,
    including the integer-ppm skew truncation and every logged
    loss/dup/reorder coin draw recomputed from the murmur3 chain;
  * the divergence-injection self-test plants a real host/device
    semantic skew (nemesis.PLANT_REORDER_OFF_BY_ONE: an off-by-one in
    the host's reorder-window span) and proves the oracle fires,
    shrinks through ddmin to the reorder clause alone, dedups two
    witnesses into ONE BugRecord, and names the first divergent
    delivery via the host causal slice — while the SAME lane without
    the plant stays green with a non-trivial draw count;
  * the serve tenant's cursors and counters survive kill/restart
    through oracle.json (torn files degrade to a reset, never a crash).
"""

import json
import os
import types

import pytest

from madsim_tpu import nemesis as nem
from madsim_tpu import oracle, triage

# --------------------------------------------------------------------------
# plans
# --------------------------------------------------------------------------

# all eight clauses, intervals tightened so every schedule-level clause
# fires inside the 3 s horizon
PLAN8 = nem.FaultPlan(name="oracle-all8", clauses=(
    nem.Crash(interval_lo_us=400_000, interval_hi_us=1_500_000,
              down_lo_us=200_000, down_hi_us=800_000),
    nem.Partition(interval_lo_us=500_000, interval_hi_us=1_800_000,
                  heal_lo_us=300_000, heal_hi_us=1_000_000),
    nem.LinkClog(interval_lo_us=600_000, interval_hi_us=2_000_000,
                 heal_lo_us=300_000, heal_hi_us=1_000_000),
    nem.LatencySpike(interval_lo_us=500_000, interval_hi_us=2_000_000,
                     duration_lo_us=200_000, duration_hi_us=800_000,
                     extra_us=80_000),
    nem.MsgLoss(rate=0.05),
    nem.Duplicate(rate=0.05),
    nem.Reorder(rate=0.15, window_us=40_000),
    nem.ClockSkew(max_ppm=30_000),
))
HOR8 = 3_000_000

# the plant-test plan: small atom universe so ddmin stays cheap, with
# enough reorder traffic that the off-by-one must surface
PLAN_PLANT = nem.FaultPlan(name="oracle-plant", clauses=(
    nem.Crash(interval_lo_us=400_000, interval_hi_us=1_500_000,
              down_lo_us=200_000, down_hi_us=800_000),
    nem.MsgLoss(rate=0.05),
    nem.Reorder(rate=0.2, window_us=40_000),
))
HOR_PLANT = 2_000_000

N, SEED = 5, 7


def _run_twin(plan, seed, horizon_us):
    run = oracle._raft_twin(seed, plan, None, N, horizon_us / 1e6, 0.1)
    return run["nemesis"]


# --------------------------------------------------------------------------
# tentpole: pure schedule == host-applied stream, all eight clauses
# --------------------------------------------------------------------------


def test_host_applies_compiled_schedule_verbatim_all_eight_clauses():
    sched = PLAN8.schedule(SEED, HOR8, N)
    kinds = {ev.kind for ev in sched}
    # every schedule-level clause fired (skew stamps at t=0)
    assert {"crash", "split", "clog", "spike_on", "skew"} <= kinds

    art = _run_twin(PLAN8, SEED, HOR8)
    expected = [ev for ev in sched if ev.kind != "skew"]
    # verbatim: same events, same order, same fields (NemesisEvent eq)
    assert list(art["applied"]) == expected

    # skew face: integer-ppm truncation, zero-ppm nodes omitted
    want_skew = {
        art["node_ids"][i]: ppm
        for i, ppm in enumerate(PLAN8.skew_ppm(SEED, N))
        if ppm != 0
    }
    assert art["node_skew"] == want_skew
    assert all(isinstance(v, int) for v in art["node_skew"].values())
    assert want_skew, "ClockSkew clause drew all-zero ppm — vacuous"


def test_every_coin_draw_matches_the_pure_chain():
    art = _run_twin(PLAN8, SEED, HOR8)
    coins = art["coins"]
    assert coins.dropped == 0
    sites_seen = {s for s, *_ in coins.draws}
    # all four message-level draw sites consumed traffic
    assert {
        nem.NET_SITE_NEM_LOSS, nem.NET_SITE_DUP, nem.NET_SITE_REORDER,
        nem.NET_SITE_REORDER_EXTRA,
    } <= sites_seen

    key = nem.key_from_seed(SEED)
    reorder = PLAN8.get(nem.Reorder)
    span = max(round(reorder.window_us / 1e6 * 1e9), 1)
    rate = {
        nem.NET_SITE_NEM_LOSS: PLAN8.get(nem.MsgLoss).rate,
        nem.NET_SITE_DUP: PLAN8.get(nem.Duplicate).rate,
        nem.NET_SITE_REORDER: reorder.rate,
    }
    for site, index, value, _t, _eid in coins.draws:
        if site == nem.NET_SITE_REORDER_EXTRA:
            assert value == nem.randint32(key, site, 0, span, index=index)
        else:
            assert value == int(nem.coin32(key, site, rate[site], index=index))


def test_check_seed_clean_tree_matches():
    rep = oracle.check_seed("raft5", PLAN8, SEED, HOR8, n_nodes=N,
                            loss_rate=0.1, repeats=2)
    assert not rep.diverged, rep.render()
    # never vacuously green: the lane exercised all surfaces
    assert rep.schedule_events > 0
    assert rep.draws > 100
    assert rep.skew_nodes > 0
    assert rep.lineage_edges > 0
    assert rep.digest
    assert rep.render().endswith("MATCH")


def test_check_seed_unknown_spec_raises():
    with pytest.raises(ValueError, match="no host twin"):
        oracle.check_seed("twopc5", PLAN_PLANT, 0, HOR_PLANT)


# --------------------------------------------------------------------------
# satellite: divergence injection — the oracle is never vacuously green
# --------------------------------------------------------------------------


def test_planted_skew_fires_and_names_first_divergent_delivery(monkeypatch):
    # the SAME lane is green without the plant...
    clean = oracle.check_seed("raft5", PLAN_PLANT, 3, HOR_PLANT, n_nodes=N,
                              repeats=1)
    assert not clean.diverged, clean.render()
    assert clean.draws > 0

    # ...and fires with it
    monkeypatch.setenv(nem.PLANT_ENV, nem.PLANT_REORDER_OFF_BY_ONE)
    rep = oracle.check_seed("raft5", PLAN_PLANT, 3, HOR_PLANT, n_nodes=N,
                            repeats=1)
    assert rep.diverged
    first = rep.first
    assert first.kind == "coin"
    assert first.site == "reorder_extra"
    assert first.applied != first.expected
    # the headline names the first divergent event, anchored into the
    # host lineage DAG
    assert first.eid >= 0
    assert first.slice_text, "divergence not anchored to a delivery"
    assert first.slice_digest is not None
    text = rep.render()
    assert "first divergent event" in text
    assert "causal slice" in text


def test_planted_skew_shrinks_to_the_reorder_clause(monkeypatch, tmp_path):
    monkeypatch.setenv(nem.PLANT_ENV, nem.PLANT_REORDER_OFF_BY_ONE)
    sr = oracle.shrink_divergence(
        "raft5", PLAN_PLANT, 3, HOR_PLANT, n_nodes=N,
        out_dir=str(tmp_path),
    )
    # 1-minimal: the off-by-one lives in the reorder window, so ddmin
    # must keep exactly that clause
    assert sr.kept_atoms == [("reorder", None)]
    b = sr.bundle
    assert b.violation_kind == "divergence"
    assert b.causal is not None and b.causal.get("sha")
    assert any("first divergent event" in ln for ln in b.trace_tail)
    # round-trips through the v3 bundle format unchanged
    loaded = triage.ReproBundle.load(sr.bundle_path)
    assert loaded.violation_kind == "divergence"
    assert loaded.plan == b.plan


def test_no_divergence_means_not_reproducible():
    with pytest.raises(triage.NotReproducible):
        oracle.shrink_divergence("raft5", PLAN_PLANT, 3, HOR_PLANT, n_nodes=N)


def test_divergence_bugs_dedup_to_one_record(monkeypatch, tmp_path):
    monkeypatch.setenv(nem.PLANT_ENV, nem.PLANT_REORDER_OFF_BY_ONE)
    camp = types.SimpleNamespace(
        bugs=[], _by_sig={}, bundles_dir=str(tmp_path),
        campaign_id="oracle-test", generation=2,
        spec_ref=None, spec_kwargs={},
    )
    seeds = []
    for s in range(3, 10):
        rep = oracle.check_seed("raft5", PLAN_PLANT, s, HOR_PLANT,
                                n_nodes=N, repeats=1)
        if rep.diverged:
            seeds.append((s, rep))
        if len(seeds) == 2:
            break
    assert len(seeds) == 2, "plant did not fire on two lanes"

    rec1 = oracle.divergence_bug(camp, seeds[0][1], PLAN_PLANT, HOR_PLANT, N)
    rec2 = oracle.divergence_bug(camp, seeds[1][1], PLAN_PLANT, HOR_PLANT, N)
    # both witnesses shrink to the same clause profile -> ONE BugRecord
    assert rec1 is rec2
    assert len(camp.bugs) == 1
    assert rec1.violation_kind == "divergence"
    assert len(rec1.witnesses) == 2
    assert all(w["origin"] == "oracle" for w in rec1.witnesses)
    assert rec1.shrink_error is None
    assert rec1.bundle_path and os.path.exists(rec1.bundle_path)
    b = triage.ReproBundle.load(rec1.bundle_path)
    assert b.violation_kind == "divergence"
    assert b.signature == rec1.signature


# --------------------------------------------------------------------------
# satellite: repro --backend both on a divergence bundle
# --------------------------------------------------------------------------


def _plant_bundle(tmp_path):
    sr = oracle.shrink_divergence(
        "raft5", PLAN_PLANT, 3, HOR_PLANT, n_nodes=N,
        out_dir=str(tmp_path),
    )
    return sr.bundle_path


def test_repro_both_reproduces_divergence_and_exits_nonzero(
    monkeypatch, tmp_path, capsys,
):
    from madsim_tpu import repro

    monkeypatch.setenv(nem.PLANT_ENV, nem.PLANT_REORDER_OFF_BY_ONE)
    path = _plant_bundle(tmp_path)
    # a reproduced divergence is a LIVE bug: readable report, non-zero exit
    rc = repro.main([path, "--backend", "both"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "first divergent event" in out
    assert "reorder_extra" in out
    assert "bit-identically across 2 schedule-matched host replays" in out


def test_repro_divergence_replay_is_differential_on_every_backend(
    monkeypatch, tmp_path,
):
    from madsim_tpu import repro

    monkeypatch.setenv(nem.PLANT_ENV, nem.PLANT_REORDER_OFF_BY_ONE)
    bundle = triage.ReproBundle.load(_plant_bundle(tmp_path))
    # tpu/host/both all route to the oracle replay — a divergence has no
    # single-backend reproduction
    for backend in ("tpu", "host", "both"):
        rep = repro.replay(bundle, backend=backend, out=lambda s: None)
        assert rep["diverged"]
        assert rep["repeats"] == 2
        assert rep["first"]["site"] == "reorder_extra"


def test_repro_divergence_stale_bundle_fails_loudly(
    monkeypatch, tmp_path, capsys,
):
    from madsim_tpu import repro

    monkeypatch.setenv(nem.PLANT_ENV, nem.PLANT_REORDER_OFF_BY_ONE)
    path = _plant_bundle(tmp_path)
    # the skew the bundle recorded is "fixed" (plant removed): the lane
    # no longer diverges and the replay must say so, not pass vacuously
    monkeypatch.delenv(nem.PLANT_ENV)
    rc = repro.main([path, "--backend", "both"])
    assert rc == 1
    err = capsys.readouterr().err
    assert "did NOT diverge" in err


# --------------------------------------------------------------------------
# satellite: the serve tenant resumes across kill/restart
# --------------------------------------------------------------------------


def test_tenant_state_survives_kill_restart(tmp_path):
    path = str(tmp_path / "oracle.json")
    t1 = oracle.OracleTenant(state_path=path)
    t1.cursor = {"c1": 5, "c2": 2}
    t1.seeds_checked = 7
    t1.divergences = 1
    t1.skipped_saturated = 3
    t1.save()

    t2 = oracle.OracleTenant(state_path=path)
    assert t2.cursor == {"c1": 5, "c2": 2}
    assert t2.seeds_checked == 7
    assert t2.divergences == 1
    assert t2.skipped_saturated == 3

    with open(path) as f:
        doc = json.load(f)
    assert doc["format"] == "madsim-tpu-oracle/1"


def test_tenant_tolerates_torn_state_file(tmp_path):
    path = str(tmp_path / "oracle.json")
    with open(path, "w") as f:
        f.write('{"format": "madsim-tpu-ora')  # killed mid-write
    t = oracle.OracleTenant(state_path=path)
    assert t.cursor == {}
    assert t.seeds_checked == 0


def test_tenant_skips_specs_without_twin():
    t = oracle.OracleTenant()
    camp = types.SimpleNamespace(spec_name="twopc5")
    out = t.observe("c1", camp)
    assert out == {"campaign": "c1", "checked": 0, "diverged": 0,
                   "skipped": 1}
    assert t.skipped_no_twin == 1


def _stub_corpus_campaign(gen, entries):
    ex = types.SimpleNamespace(corpus=[
        types.SimpleNamespace(
            cand=types.SimpleNamespace(seed=s),
            dispatch=d,
        )
        for s, d in entries
    ])
    return types.SimpleNamespace(generation=gen, ex=ex)


def test_tenant_sampling_is_deterministic_and_cursor_advances():
    entries = [(s, g) for g in range(3) for s in range(g * 10, g * 10 + 6)]
    a = oracle.OracleTenant(sample_rate=0.5)
    b = oracle.OracleTenant(sample_rate=0.5)
    camp = _stub_corpus_campaign(3, entries)
    sa = a._sampled("c", camp)
    sb = b._sampled("c", camp)
    # pure in (seed, generation): two services agree on the lane set
    assert sa == sb
    assert 0 < len(sa) < len(entries)
    # the cursor consumed generations [0, 3) — same round resamples nothing
    assert a._sampled("c", camp) == []
    # new generations only: entries below the cursor never re-sample
    camp2 = _stub_corpus_campaign(4, entries + [(99, 3)])
    again = a._sampled("c", camp2)
    assert all(s == 99 for s in again)
