"""The ecosystem capstone example (examples/pipeline.py) under test: all
four facades in one app, exactly-once through leader failover, per-seed
deterministic."""

import pytest

import madsim_tpu as ms

# repo root is on sys.path via tests/conftest.py, which also resolves
# the examples package
from examples.pipeline import run_pipeline


@pytest.mark.parametrize("seed", [1, 5])
def test_pipeline_exactly_once_through_failover(seed):
    rt = ms.Runtime(seed=seed)
    r = rt.block_on(run_pipeline(rt))
    assert r["exactly_once"], r
    # the chaos actually bit: leadership moved at least once
    assert r["failovers"] >= 1, r
    assert r["kills"], r


def test_pipeline_deterministic():
    results = []
    for _ in range(2):
        rt = ms.Runtime(seed=3)
        results.append(rt.block_on(run_pipeline(rt)))
    assert results[0] == results[1]
