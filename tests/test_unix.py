"""Unix domain socket sim tests.

The reference stubs these entirely (net/unix/ is `todo!()`); this suite
covers the working implementation: stream + datagram roundtrips, the
HOST-LOCAL (per-node) path namespace, socketpair, and path release on
node kill/restart.
"""

import pytest

import madsim_tpu as ms
from madsim_tpu.net import UnixDatagram, UnixListener, UnixStream


def test_stream_roundtrip_and_eof():
    rt = ms.Runtime(seed=1)

    async def main():
        node = rt.handle.create_node().name("n").build()

        async def server():
            listener = await UnixListener.bind("/tmp/echo.sock")
            stream, _peer = await listener.accept()
            data = await stream.read_exact(5)
            await stream.write_all(data[::-1])
            stream.shutdown()

        node.spawn(server())

        async def client():
            await ms.time.sleep(0.1)
            s = await UnixStream.connect("/tmp/echo.sock")
            await s.write_all(b"hello")
            assert await s.read_exact(5) == b"olleh"
            assert await s.read() == b""  # EOF after peer shutdown
            return True

        return await node.spawn(client())

    assert rt.block_on(main())


def test_path_namespace_is_per_node():
    rt = ms.Runtime(seed=2)

    async def main():
        a = rt.handle.create_node().name("a").build()
        b = rt.handle.create_node().name("b").build()

        async def bind_it():
            await UnixListener.bind("/run/app.sock")
            return True

        # the same path binds independently on two nodes (host-local fs)
        assert await a.spawn(bind_it())
        assert await b.spawn(bind_it())

        async def connect_it():
            with pytest.raises(ConnectionRefusedError):
                await UnixStream.connect("/run/other.sock")
            return True

        assert await a.spawn(connect_it())

        # double-bind on ONE node is the error the kernel gives
        async def rebind():
            with pytest.raises(OSError, match="already in use"):
                await UnixListener.bind("/run/app.sock")
            return True

        assert await a.spawn(rebind())

    rt.block_on(main())


def test_datagram_roundtrip():
    rt = ms.Runtime(seed=3)

    async def main():
        node = rt.handle.create_node().name("n").build()

        async def server():
            dg = await UnixDatagram.bind("/tmp/dg.sock")
            data, frm = await dg.recv_from()
            assert frm == "/tmp/client.sock"
            await dg.send_to(data.upper(), frm)

        node.spawn(server())

        async def client():
            await ms.time.sleep(0.1)
            dg = await UnixDatagram.bind("/tmp/client.sock")
            dg.connect("/tmp/dg.sock")
            await dg.send(b"ping")
            assert await dg.recv() == b"PING"
            return True

        return await node.spawn(client())

    assert rt.block_on(main())


def test_socketpair():
    rt = ms.Runtime(seed=4)

    async def main():
        node = rt.handle.create_node().name("n").build()

        async def body():
            a, b = UnixStream.pair()
            await a.write_all(b"x")
            assert await b.read_exact(1) == b"x"
            await b.write_all(b"y")
            assert await a.read_exact(1) == b"y"
            return True

        return await node.spawn(body())

    assert rt.block_on(main())


def test_kill_releases_paths():
    rt = ms.Runtime(seed=5)

    async def main():
        h = rt.handle
        victim = h.create_node().name("victim").build()

        async def bind_forever():
            await UnixListener.bind("/srv/sock")
            await ms.time.sleep(1e9)

        victim.spawn(bind_forever())
        other = h.create_node().name("other").build()

        async def driver():
            await ms.time.sleep(0.1)
            h.kill(victim.id)
            await ms.time.sleep(0.1)
            return True

        assert await other.spawn(driver())

        # a dead process's sockets vanish with it: the path is free again
        async def rebind():
            await UnixListener.bind("/srv/sock")
            return True

        h.restart(victim.id)
        assert await victim.spawn(rebind())

    rt.block_on(main())
