"""Determinism substrate tests: stdlib time/random/urandom/uuid virtualized
inside a sim, real threads/event loops/blocking sleeps forbidden.

Mirrors the reference's libc-interposition tests (rand.rs:265-308,
time/system_time.rs:112-152, task/mod.rs:753-769 thread guard)."""

import asyncio
import os
import random
import threading
import time
import uuid

import pytest

import madsim_tpu as ms
from madsim_tpu.core.interpose import SimForbiddenError


def stdlib_trace(seed):
    """User code that uses ONLY the stdlib for time + entropy."""
    rt = ms.Runtime(seed=seed)

    async def main():
        trace = []
        trace.append(("time", time.time()))
        trace.append(("mono", time.monotonic()))
        await ms.time.sleep(1.5)
        trace.append(("time2", time.time()))
        trace.append(("rand", random.random()))
        trace.append(("randint", random.randint(0, 10**9)))
        trace.append(("gauss", random.gauss(0.0, 1.0)))
        trace.append(("urandom", os.urandom(16)))
        trace.append(("uuid", str(uuid.uuid4())))
        trace.append(("shuffled", random.sample(list(range(20)), 20)))
        r = random.Random()  # seeds itself from (patched) urandom
        trace.append(("instance", r.random()))
        return trace

    return rt.block_on(main())


def test_stdlib_time_and_random_bit_identical_across_runs():
    a = stdlib_trace(42)
    b = stdlib_trace(42)
    assert a == b


def test_different_seed_diverges():
    assert stdlib_trace(42) != stdlib_trace(43)


def test_virtual_time_advances_with_sim():
    rt = ms.Runtime(seed=1)

    async def main():
        t0 = time.time()
        m0 = time.monotonic()
        await ms.time.sleep(5.0)
        return time.time() - t0, time.monotonic() - m0

    dt, dm = rt.block_on(main())
    assert abs(dt - 5.0) < 0.01
    assert abs(dm - 5.0) < 0.01


def test_system_time_base_is_2022ish():
    rt = ms.Runtime(seed=9)

    async def main():
        return time.time()

    t = rt.block_on(main())
    # random base date within year 2022 (reference time/mod.rs:26-36)
    assert 52 * 365 * 86400 < t < 54 * 365 * 86400


def test_passthrough_outside_sim():
    # ensure patches are installed, then verify passthrough semantics
    ms.Runtime(seed=1)
    assert abs(time.time() - time.time()) < 1.0
    assert time.monotonic() <= time.monotonic()
    assert len(os.urandom(8)) == 8
    random.random()  # must not raise
    t = threading.Thread(target=lambda: None)
    t.start()
    t.join()


def test_thread_spawn_forbidden_inside_sim():
    rt = ms.Runtime(seed=1)

    async def main():
        t = threading.Thread(target=lambda: None)
        with pytest.raises(SimForbiddenError, match="real thread"):
            t.start()
        return True

    assert rt.block_on(main())


def test_asyncio_run_forbidden_inside_sim():
    rt = ms.Runtime(seed=1)

    async def main():
        async def inner():
            return 1

        coro = inner()
        with pytest.raises(SimForbiddenError, match="asyncio"):
            asyncio.run(coro)
        coro.close()
        return True

    assert rt.block_on(main())


def test_blocking_sleep_forbidden_inside_sim():
    rt = ms.Runtime(seed=1)

    async def main():
        with pytest.raises(SimForbiddenError, match="time.sleep"):
            time.sleep(0.01)
        return True

    assert rt.block_on(main())


def test_reseeding_global_random_is_ignored_inside_sim():
    rt = ms.Runtime(seed=5)

    async def main():
        random.seed(1234)  # must NOT make the stream reproducible across seeds
        return random.random()

    rt2 = ms.Runtime(seed=6)

    async def main2():
        random.seed(1234)
        return random.random()

    assert rt.block_on(main()) != rt2.block_on(main2())


def test_datetime_now_is_virtual_inside_sim():
    """The r3 documented determinism hole, closed: datetime.datetime.now /
    utcnow / today and datetime.date.today read the VIRTUAL clock in-sim
    (bit-identical across runs, advancing with simulated sleeps) and the
    real clock outside (time/system_time.rs:4-110 parity)."""
    import datetime

    rt = ms.Runtime(seed=7)

    async def main():
        a = datetime.datetime.now()
        await ms.time.sleep(5.0)
        b = datetime.datetime.now()
        return a, b, datetime.datetime.utcnow(), datetime.datetime.today(), \
            datetime.date.today()

    a, b, utc, today, d = rt.block_on(main())
    assert abs((b - a).total_seconds() - 5.0) < 0.01
    assert today.date() == a.date()
    assert d == a.date()
    # bit-identical across runs of the same seed
    rt2 = ms.Runtime(seed=7)
    a2, b2, utc2, today2, d2 = rt2.block_on(main())
    assert (a, b, utc, today, d) == (a2, b2, utc2, today2, d2)
    # the virtual base date is 2022ish (reference time/mod.rs:26-36)
    assert a.year in (2022, 2023)


def test_datetime_passthrough_and_type_sanity_outside_sim():
    import datetime

    ms.Runtime(seed=1)  # patches installed
    real = datetime.datetime.now()
    wall = time.time()
    assert abs(real.timestamp() - wall) < 5.0
    # isinstance semantics survive the subclass install: plain instances
    # (constructed before/after install, parsed, arithmetic results) still
    # satisfy checks against the patched classes
    plain = datetime.datetime(2020, 1, 2, 3, 4, 5)
    assert isinstance(plain, datetime.datetime)
    assert isinstance(plain, datetime.date)
    assert isinstance(real, datetime.datetime)
    assert isinstance(real + datetime.timedelta(days=1), datetime.datetime)
    assert isinstance(datetime.date(2020, 1, 2), datetime.date)
