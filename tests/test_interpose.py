"""Determinism substrate tests: stdlib time/random/urandom/uuid virtualized
inside a sim, real threads/event loops/blocking sleeps forbidden.

Mirrors the reference's libc-interposition tests (rand.rs:265-308,
time/system_time.rs:112-152, task/mod.rs:753-769 thread guard)."""

import asyncio
import os
import random
import threading
import time
import uuid

import pytest

import madsim_tpu as ms
from madsim_tpu.core.interpose import SimForbiddenError


def stdlib_trace(seed):
    """User code that uses ONLY the stdlib for time + entropy."""
    rt = ms.Runtime(seed=seed)

    async def main():
        trace = []
        trace.append(("time", time.time()))
        trace.append(("mono", time.monotonic()))
        await ms.time.sleep(1.5)
        trace.append(("time2", time.time()))
        trace.append(("rand", random.random()))
        trace.append(("randint", random.randint(0, 10**9)))
        trace.append(("gauss", random.gauss(0.0, 1.0)))
        trace.append(("urandom", os.urandom(16)))
        trace.append(("uuid", str(uuid.uuid4())))
        trace.append(("shuffled", random.sample(list(range(20)), 20)))
        r = random.Random()  # seeds itself from (patched) urandom
        trace.append(("instance", r.random()))
        return trace

    return rt.block_on(main())


def test_stdlib_time_and_random_bit_identical_across_runs():
    a = stdlib_trace(42)
    b = stdlib_trace(42)
    assert a == b


def test_different_seed_diverges():
    assert stdlib_trace(42) != stdlib_trace(43)


def test_virtual_time_advances_with_sim():
    rt = ms.Runtime(seed=1)

    async def main():
        t0 = time.time()
        m0 = time.monotonic()
        await ms.time.sleep(5.0)
        return time.time() - t0, time.monotonic() - m0

    dt, dm = rt.block_on(main())
    assert abs(dt - 5.0) < 0.01
    assert abs(dm - 5.0) < 0.01


def test_system_time_base_is_2022ish():
    rt = ms.Runtime(seed=9)

    async def main():
        return time.time()

    t = rt.block_on(main())
    # random base date within year 2022 (reference time/mod.rs:26-36)
    assert 52 * 365 * 86400 < t < 54 * 365 * 86400


def test_passthrough_outside_sim():
    # ensure patches are installed, then verify passthrough semantics
    ms.Runtime(seed=1)
    assert abs(time.time() - time.time()) < 1.0
    assert time.monotonic() <= time.monotonic()
    assert len(os.urandom(8)) == 8
    random.random()  # must not raise
    t = threading.Thread(target=lambda: None)
    t.start()
    t.join()


def test_thread_spawn_forbidden_inside_sim():
    rt = ms.Runtime(seed=1)

    async def main():
        t = threading.Thread(target=lambda: None)
        with pytest.raises(SimForbiddenError, match="real thread"):
            t.start()
        return True

    assert rt.block_on(main())


def test_asyncio_run_forbidden_inside_sim():
    rt = ms.Runtime(seed=1)

    async def main():
        async def inner():
            return 1

        coro = inner()
        with pytest.raises(SimForbiddenError, match="asyncio"):
            asyncio.run(coro)
        coro.close()
        return True

    assert rt.block_on(main())


def test_blocking_sleep_forbidden_inside_sim():
    rt = ms.Runtime(seed=1)

    async def main():
        with pytest.raises(SimForbiddenError, match="time.sleep"):
            time.sleep(0.01)
        return True

    assert rt.block_on(main())


def test_reseeding_global_random_is_ignored_inside_sim():
    rt = ms.Runtime(seed=5)

    async def main():
        random.seed(1234)  # must NOT make the stream reproducible across seeds
        return random.random()

    rt2 = ms.Runtime(seed=6)

    async def main2():
        random.seed(1234)
        return random.random()

    assert rt.block_on(main()) != rt2.block_on(main2())
