"""Speclang: one spec source compiles to BOTH faces, provably.

The bar these tests pin (docs/speclang.md):

  * re-derivation is EXACT — the twopc and lease spec sources compile to
    device programs bit-identical to the hand-written `tpu/<x>.py`
    modules, witnessed by the canonical golden trajectory digests of
    tests/test_state_layout.py (same chaotic plan, same lanes, same
    steps — same sha256);
  * derivation replaces restatement — narrow tables, rate floors, the
    safe narrow horizon, kind vocabulary and the durable plane all come
    from declarations, and they agree with what the hand modules state
    by hand;
  * the generated modules are pinned to their sources — `emit --check`
    is clean and every `SPECLANG_DIGEST` matches the current source
    sha256 (the registry mirror lint enforces the same thing in CI);
  * the restricted language refuses at authoring time exactly what the
    verifier tiers exist to catch at trace time;
  * the one speclang-NATIVE protocol (primary-backup log shipping,
    specs/backup.py) carries a plantable stale-read bug that the
    explorer finds and ddmin shrinks to its message-clause axis.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from madsim_tpu import nemesis, triage
from madsim_tpu import workloads as registry
from madsim_tpu.analysis import lint
from madsim_tpu.speclang import device, emit, lang
from madsim_tpu.speclang.specs import PROTOCOLS
from madsim_tpu.speclang.specs import backup as s_backup
from madsim_tpu.speclang.specs import lease as s_lease
from madsim_tpu.speclang.specs import twopc as s_twopc
from madsim_tpu.tpu import nemesis as tpu_nemesis
from madsim_tpu.tpu.engine import BatchedSim, summarize
from madsim_tpu.tpu.lease import make_lease_spec
from madsim_tpu.tpu.spec import SimConfig
from madsim_tpu.tpu.twopc import make_twopc_spec
from tests import test_state_layout as tsl


def _chaos_run(spec, plan=None, lanes=16, steps=1500):
    cfg = tpu_nemesis.compile_plan(
        plan or tsl.CHAOS_PLAN, SimConfig(horizon_us=30_000_000)
    )
    sim = BatchedSim(spec, cfg)
    return sim.run(
        jnp.arange(lanes, dtype=jnp.uint32),
        max_steps=steps, dispatch_steps=steps,
    )


# ------------------------------------------------------ bit-identity bar


@pytest.mark.chaos
def test_generated_twopc_matches_golden_digest():
    """The compiler bar: the twopc re-derivation runs bit-identically to
    the hand module — its chaotic trajectory hashes to the SAME pinned
    golden constant (layout-version r8) the hand spec is held to."""
    st = _chaos_run(device.build(s_twopc.PROTOCOL))
    assert tsl.canonical_digest(st) == tsl.GOLDEN["twopc"], (
        "speclang twopc re-derivation diverged from the hand module's "
        "pinned golden trajectory"
    )
    assert summarize(st)["total_events"] > 0


# every message clause armed on top of the layout plan: digest equality
# below then covers nemesis fire counters and dup/reorder draw streams,
# not just node state
RICH_PLAN = nemesis.FaultPlan(
    name="speclang-rich",
    clauses=tsl.CHAOS_PLAN.clauses + (
        nemesis.Duplicate(rate=0.1),
        nemesis.Reorder(rate=0.2, window_us=120_000),
    ),
)


@pytest.mark.chaos
def test_generated_lease_matches_hand_digest():
    """lease authors as two handlers (fused=False) and the compiler
    routes it through fuse_two_handlers — still bit-identical to the
    hand spec, under a plan arming every message clause."""
    hand = _chaos_run(make_lease_spec(), plan=RICH_PLAN)
    gen = _chaos_run(device.build(s_lease.PROTOCOL), plan=RICH_PLAN)
    assert tsl.canonical_digest(gen) == tsl.canonical_digest(hand)
    assert summarize(gen)["total_events"] > 0


def _floor_view(floors):
    out = {}
    for name, fl in (floors or {}).items():
        out[name] = (
            type(fl).__name__,
            tuple(
                (a, getattr(fl, a))
                for a in ("floor_us", "ratchet", "inc", "cap")
                if hasattr(fl, a)
            ),
        )
    return out


@pytest.mark.parametrize(
    "proto,hand_factory",
    [(s_twopc.PROTOCOL, make_twopc_spec),
     (s_lease.PROTOCOL, make_lease_spec)],
    ids=["twopc", "lease"],
)
def test_derived_tables_match_hand_specs(proto, hand_factory):
    """Every table the hand modules restate by hand is DERIVED from the
    declarations — and lands on the same values (the `why` prose is the
    one field allowed to differ)."""
    gen, hand = device.build(proto), hand_factory()
    assert gen.n_nodes == hand.n_nodes
    assert gen.payload_width == hand.payload_width
    assert (gen.max_out, gen.max_out_msg) == (hand.max_out, hand.max_out_msg)
    assert gen.narrow_fields == hand.narrow_fields
    assert gen.narrow_horizon_us == hand.narrow_horizon_us
    assert tuple(gen.time_fields or ()) == tuple(hand.time_fields or ())
    assert tuple(gen.msg_kind_names) == tuple(hand.msg_kind_names)
    assert _floor_view(gen.rate_floors) == _floor_view(hand.rate_floors)
    assert tuple(gen.durable_fields or ()) == tuple(hand.durable_fields or ())
    assert gen.sync_field == hand.sync_field


# --------------------------------------------------- emit + registry pins


def test_emit_check_clean():
    """The checked-in generated modules are exactly what the current
    spec sources render to (the `make speclang-smoke` drift gate)."""
    clean, drifted = emit.emit(check=True)
    assert not drifted, f"generated modules drifted: {drifted}"
    assert len(clean) == 2 * len(PROTOCOLS)


def test_generated_modules_pin_source_digest():
    from madsim_tpu.speclang.generated import (
        backup_device, backup_host, lease_device, lease_host,
        twopc_device, twopc_host,
    )

    for mod, src in (
        (twopc_device, "twopc"), (twopc_host, "twopc"),
        (lease_device, "lease"), (lease_host, "lease"),
        (backup_device, "backup"), (backup_host, "backup"),
    ):
        assert mod.SPECLANG_DIGEST == emit.source_digest(src)


def test_workload_registry_mirror_lint_clean():
    """The registry mirror lint (analysis.lint.check_workload_registry):
    every row resolves on every declared face, the consumers import the
    registry, and the generated rows' digests pin their sources."""
    res = lint.check_workload_registry()
    assert res.rule == "mirror"
    assert res.checked >= 20
    assert not res.violations, res.violations


def test_registry_generated_rows_resolve():
    assert registry.names(generated=True) == (
        "twopc-gen", "lease-gen", "backup",
    )
    spec = registry.spec_factory("backup")()
    assert spec.name == "backup5"
    assert spec.durable_fields  # the spec source's disk plane landed
    # Tier-B knob hooks derive from the spec source's KnobDecl rows
    knobs = registry.spec_knobs("twopc-gen", 2.0)
    assert [k.name for k in knobs] == ["txn_ring"]
    wl = registry.workload_factory("twopc-gen")(virtual_secs=2.0)
    wl8 = knobs[0].rebuild(wl, 8)
    assert wl8.spec.name == wl.spec.name
    assert wl8.config == wl.config  # knobs rebuild the spec, not the plan


# ------------------------------------------------- language restrictions


def test_restriction_walk_refuses_bad_bodies():
    """The restricted language refuses at authoring time what the
    verifier tiers catch at trace time: unbounded loops, host
    callbacks, computed draw sites, ambient entropy."""
    from tests.fixtures import speclang_bad

    with pytest.raises(ValueError) as ei:
        lang.validate_protocol(speclang_bad.PROTOCOL)
    msg = str(ei.value)
    for needle in (
        "while loop",
        "host callback",
        "site must be an int literal",
        "ambient-entropy import",
    ):
        assert needle in msg, f"missing restriction finding: {needle!r}"


def test_resolve_refuses_unknown_params():
    with pytest.raises(ValueError, match="unknown spec params"):
        device.build(s_backup.PROTOCOL, nonesuch=3)


def test_fused_spec_stale_wrapper_guard():
    """Regression for the fuse_two_handlers footgun: a bare
    `dataclasses.replace(spec, on_message=...)` on a fused spec used to
    produce a handler the engine silently never ran; it must now refuse
    at construction (ProtocolSpec.__post_init__)."""
    spec = device.build(s_twopc.PROTOCOL)

    def patched(s, nid, src, kind, payload, now, key):
        return spec.on_message(s, nid, src, kind, payload, now, key)

    with pytest.raises(ValueError, match="does not derive"):
        dataclasses.replace(spec, on_message=patched)


# ------------------------------------- the speclang-native protocol's bug


@pytest.mark.chaos
def test_backup_planted_bug_fires_only_when_planted():
    """specs/backup.py's stale-read bug (apply guard `!=` instead of
    `>`): the buggy build violates monotone reads across many lanes
    under its dup/reorder workload; the correct build stays clean under
    the identical plan."""
    wl = device.build_workload(s_backup.PROTOCOL, buggy=True)
    st = BatchedSim(wl.spec, wl.config).run(
        jnp.arange(64, dtype=jnp.uint32),
        max_steps=2000, dispatch_steps=2000,
    )
    assert int(np.asarray(st.violated).sum()) >= 5

    wl0 = device.build_workload(s_backup.PROTOCOL)
    st0 = BatchedSim(wl0.spec, wl0.config).run(
        jnp.arange(64, dtype=jnp.uint32),
        max_steps=2000, dispatch_steps=2000,
    )
    assert int(np.asarray(st0.violated).sum()) == 0
    assert int(np.asarray(st0.events).sum()) > 0


@pytest.mark.deep
@pytest.mark.chaos
def test_backup_bug_explorer_finds_and_ddmin_shrinks(tmp_path):
    """The full pipeline over the generated workload: the explorer
    surfaces the planted bug, ddmin shrinks it, and the shrunk plan
    keeps the message-clause axis the bug actually needs (a stale REPL
    landing after a newer apply) — crash/restart alone cannot fire it."""
    from madsim_tpu.explore import Explorer

    wl = device.build_workload(s_backup.PROTOCOL, buggy=True)
    ex = Explorer(
        wl, meta_seed=0, lanes=64, shrink_violations=True,
        max_shrinks=1, shrink_kwargs={"out_dir": str(tmp_path)},
    )
    rep = ex.run(1)
    assert rep.violations, "planted stale-read bug not found in 64 lanes"
    shrunk = [v for v in rep.violations if v.get("bundle_path")]
    assert shrunk
    bundle = triage.ReproBundle.load(shrunk[0]["bundle_path"])
    assert bundle.violation_step > 0
    kept = {
        type(c).__name__
        for c in triage.plan_from_json(bundle.plan).clauses
    }
    assert kept & {"Duplicate", "Reorder"}, (
        f"shrunk plan {sorted(kept)} lost the message-clause axis the "
        "stale-read bug requires"
    )
