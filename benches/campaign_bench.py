"""Campaign-layer overheads: what persistence costs, and what cmin saves.

The campaign layer's pitch (docs/campaign.md) is that persistence is
near-free on this backend because a corpus entry is just a genome and a
checkpoint is an exact replayable cursor. This bench puts numbers on that:

    checkpoint_write_s / resume_load_s   full checkpoint round-trip wall
                                         (corpus + union + seen + manifest)
    resume_fingerprint_ok                the STRUCTURAL claim: resume(k).
                                         run(k') fingerprints identically
                                         to the uninterrupted k+k' run
    corpus_entries / corpus_bytes        what the checkpoint carries
    cmin_candidates / cmin_kept          merged-corpus minimization: lanes
    cmin_replay_s / cmin_dispatches      replayed (one batched program),
                                         kept fraction, union preserved
    slice_overhead_pct                   (checkpoint + resume) vs one
                                         explorer generation's wall

Structural on CPU containers like every r6+ bench: the assertions (not the
wall numbers) are the contract — fingerprint match and union preservation
are hard failures, wall-clock is reported, never asserted.

Usage: python benches/campaign_bench.py [--lanes 64] [--generations 3]
Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time


def _repo_root_on_path() -> None:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)


_repo_root_on_path()


def storm_raft_workload(virtual_secs: float = 2.0):
    """A clean (no planted bug) raft config under the storm plan: the
    campaign machinery exercised end to end without shrink costs."""
    from madsim_tpu.explore import _named_workload

    return _named_workload("raft", virtual_secs, True)


def bench_campaign(
    lanes: int = 64, generations: int = 3, virtual_secs: float = 2.0,
) -> dict:
    import numpy as np

    from madsim_tpu import campaign
    from madsim_tpu.explore import popcount_rows
    from madsim_tpu.tpu.engine import BatchedSim

    wl = storm_raft_workload(virtual_secs)
    sim = BatchedSim(wl.spec, wl.config, triage=True, coverage=True)
    root = tempfile.mkdtemp(prefix="campaign_bench_")
    out: dict = {"lanes": lanes, "generations": generations}
    try:
        # -- uninterrupted reference + per-generation wall --------------
        t0 = time.perf_counter()
        full = campaign.Campaign(
            wl, os.path.join(root, "full"), meta_seed=0, lanes=lanes,
            shrink=False, sim=sim,
        )
        rep_full = full.run(generations)
        gen_wall_s = (time.perf_counter() - t0) / max(generations, 1)
        out["generation_wall_s"] = round(gen_wall_s, 3)
        out["coverage_bits"] = rep_full.coverage_bits
        out["corpus_entries"] = rep_full.corpus_size

        # -- checkpoint write / resume load -----------------------------
        part = campaign.Campaign(
            wl, os.path.join(root, "part"), meta_seed=0, lanes=lanes,
            shrink=False, sim=sim,
        )
        part.run(max(generations - 1, 1))
        t0 = time.perf_counter()
        part.checkpoint()
        out["checkpoint_write_s"] = round(time.perf_counter() - t0, 4)
        out["corpus_bytes"] = sum(
            os.path.getsize(os.path.join(root, "part", f))
            for f in os.listdir(os.path.join(root, "part"))
            if os.path.isfile(os.path.join(root, "part", f))
        )
        t0 = time.perf_counter()
        resumed = campaign.Campaign.resume(
            os.path.join(root, "part"), workload=wl, sim=sim
        )
        out["resume_load_s"] = round(time.perf_counter() - t0, 4)
        rep_res = resumed.run(
            generations - max(generations - 1, 1)
        ) if generations > 1 else resumed.report()
        ok = rep_res.fingerprint() == rep_full.fingerprint()
        out["resume_fingerprint_ok"] = ok
        assert ok, "resume diverged from the uninterrupted run"
        out["slice_overhead_pct"] = round(
            100 * (out["checkpoint_write_s"] + out["resume_load_s"])
            / max(gen_wall_s, 1e-9), 2,
        )

        # -- merge + cmin -----------------------------------------------
        campaign.export_explorer(
            os.path.join(root, "a"), full.ex, {"kind": "custom"}
        )
        campaign.export_explorer(
            os.path.join(root, "b"), resumed.ex, {"kind": "custom"}
        )
        entries, _ = campaign.merge_corpora(
            [os.path.join(root, "a"), os.path.join(root, "b")]
        )
        t0 = time.perf_counter()
        res = campaign.minimize(
            wl, entries, sim=sim, lane_width=min(lanes, 64)
        )
        out["cmin_replay_s"] = round(time.perf_counter() - t0, 3)
        out["cmin_candidates"] = res["replayed"]
        out["cmin_kept"] = len(res["kept"])
        out["cmin_dispatches"] = res["dispatches"]
        out["cmin_union_bits"] = res["merged_bits"]
        # the union-preservation assertion already ran inside minimize();
        # re-assert here so the bench is a standalone witness
        union = np.zeros_like(res["union"])
        for e in res["kept"]:
            union |= e.bitmap
        assert int(popcount_rows(union[None, :])[0]) == res["merged_bits"]
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return out


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--lanes", type=int, default=64)
    parser.add_argument("--generations", type=int, default=3)
    parser.add_argument("--virtual-secs", type=float, default=2.0)
    args = parser.parse_args()
    print(
        json.dumps(bench_campaign(
            args.lanes, args.generations, args.virtual_secs
        )),
        flush=True,
    )


if __name__ == "__main__":
    main()
