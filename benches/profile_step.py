"""Phase-level profile of the BatchedSim step on the current backend.

Times the full jitted step at the bench config, then ablated variants
(invariant check off, handlers off, network pack off) to attribute cost.
Ablations are rough — XLA fuses across phases, so an "ablated" phase's
cost includes whatever fusion it enabled — but they rank the suspects.

Usage: python benches/profile_step.py [--lanes 32768] [--reps 30]
"""

from __future__ import annotations

import argparse
import json
import time


def timeit(fn, state, reps):
    out = fn(state)
    import jax

    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(out) if isinstance(out, type(state)) else fn(state)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--lanes", type=int, default=32768)
    parser.add_argument("--reps", type=int, default=30)
    parser.add_argument("--protocol", default="raft")
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    from madsim_tpu.tpu import BatchedSim, SimConfig, make_raft_spec

    if args.protocol == "raft":
        spec = make_raft_spec(n_nodes=5, client_rate=0.1)
    else:
        from madsim_tpu.tpu.kv import make_kv_spec

        spec = make_kv_spec(n_nodes=5)
    cfg = SimConfig(
        horizon_us=10_000_000,
        msg_capacity=128,
        loss_rate=0.10,
        crash_interval_lo_us=500_000,
        crash_interval_hi_us=3_000_000,
        restart_delay_lo_us=300_000,
        restart_delay_hi_us=2_000_000,
        partition_interval_lo_us=300_000,
        partition_interval_hi_us=1_500_000,
        partition_heal_lo_us=500_000,
        partition_heal_hi_us=2_000_000,
    )
    sim = BatchedSim(spec, cfg)
    print(
        f"C={sim._C} Km={sim._Km} Kt={sim._Kt} CK={sim._CK} B={sim._B} N={spec.n_nodes} "
        f"P={spec.payload_width} lanes={args.lanes}",
        flush=True,
    )
    state = sim.init(jnp.arange(args.lanes))
    # warm the state into a realistic regime (pool part-full, roles mixed)
    state = sim.run_steps(state, 200)
    jax.block_until_ready(state)

    step = jax.jit(sim._step)
    full = timeit(step, state, args.reps)
    print(json.dumps({"phase": "full_step", "ms": round(full * 1e3, 3)}), flush=True)

    # cost analysis from XLA
    lowered = jax.jit(sim._step).lower(state)
    compiled = lowered.compile()
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        print(
            json.dumps(
                {
                    "flops": ca.get("flops"),
                    "bytes_accessed": ca.get("bytes accessed"),
                    "transcendentals": ca.get("transcendentals"),
                }
            ),
            flush=True,
        )
    except Exception as e:  # noqa: BLE001
        print(f"cost_analysis unavailable: {e}", flush=True)

    print(f"events/step estimate: run 1 step on warmed state", flush=True)
    s2 = step(state)
    ev = int(jax.device_get(s2.events.sum() - state.events.sum()))
    print(
        json.dumps(
            {
                "events_per_step_total": ev,
                "events_per_step_per_lane": ev / args.lanes,
                "us_per_step": round(full * 1e6, 1),
                "events_per_sec": round(ev / full, 1),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
