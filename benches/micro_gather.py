"""Micro-bench: per-node field extraction — one-hot contraction vs
take_along_axis gather, at the dest-major pool shape [L, N, R(, P)].
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

L, N, R, P = 32768, 5, 64, 6


def timeit(fn, *args, reps=50):
    out = fn(*args)
    jax.block_until_ready(out)
    best = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args)
        jax.block_until_ready(out)
        best.append((time.perf_counter() - t0) / reps)
    return sorted(best)[1]


def main():
    k = jax.random.PRNGKey(0)
    pay = jax.random.randint(k, (L, N, R, P), 0, 1 << 20, dtype=jnp.int32)
    kind = jax.random.randint(k, (L, N, R), 0, 5, dtype=jnp.int32)
    slot = jax.random.randint(k, (L, N), 0, R, dtype=jnp.int32)

    @jax.jit
    def onehot(pay, kind, slot):
        oh = (jnp.arange(R)[None, None, :] == slot[:, :, None]).astype(jnp.int32)
        m_kind = (kind * oh).sum(-1)
        m_pay = (pay * oh[:, :, :, None]).sum(2)
        return m_kind, m_pay

    @jax.jit
    def gather(pay, kind, slot):
        m_kind = jnp.take_along_axis(kind, slot[:, :, None], axis=2)[:, :, 0]
        m_pay = jnp.take_along_axis(
            pay, slot[:, :, None, None], axis=2
        )[:, :, 0, :]
        return m_kind, m_pay

    t1 = timeit(onehot, pay, kind, slot)
    t2 = timeit(gather, pay, kind, slot)
    print(json.dumps({"onehot_ms": round(t1 * 1e3, 3),
                      "gather_ms": round(t2 * 1e3, 3)}))

    # min-reduce over R per (l, n): the pick phase at dest-major layout
    deliver = jax.random.randint(k, (L, N, R), 0, 1 << 30, dtype=jnp.int32)
    valid = jax.random.bernoulli(k, 0.3, (L, N, R))

    @jax.jit
    def pick(deliver, valid):
        t = jnp.where(valid, deliver, jnp.int32(2**31 - 1))
        tmin = t.min(-1)
        slot = jnp.argmin(t, -1)
        return tmin, slot

    t3 = timeit(pick, deliver, valid)
    print(json.dumps({"pick_ms": round(t3 * 1e3, 3)}))

    # int64 variant of the same pick — part of the measurement behind the
    # engine's epoch+offset time design (int64 min/argmin measures ~2-3x
    # slower than int32 here, plus doubles the memory of every time
    # tensor; spec.REBASE_US keeps the hot path int32)
    with jax.enable_x64(True):
        deliver64 = deliver.astype(jnp.int64) + jnp.int64(2**40)

        @jax.jit
        def pick64(deliver, valid):
            t = jnp.where(valid, deliver, jnp.int64(2**62))
            tmin = t.min(-1)
            slot = jnp.argmin(t, -1)
            return tmin, slot

        t4 = timeit(pick64, deliver64, valid)
    print(json.dumps({"pick64_ms": round(t4 * 1e3, 3),
                      "pick64_vs_pick32": round(t4 / t3, 1)}))


if __name__ == "__main__":
    main()
