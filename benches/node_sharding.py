"""1-D (lanes) vs 2-D (lanes x nodes) mesh sharding, measured.

VERDICT r4 weak #4: `shard_state(node_axis=...)` existed but was
compile-tested only — no measurement of when node sharding wins or what
the cross-node gathers cost. This experiment runs the raft fuzz step on
a forced 8-device CPU mesh at growing cluster sizes and times 60-step
scans (after a 10-step warmup) under three layouts:

    lanes8   — 1-D: all 8 devices shard the lane axis (no collectives)
    mixed2x4 — 2-D: 2-way lanes x 4-way nodes
    nodes8   — node-axis only (the TP-analog extreme)

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
         python benches/node_sharding.py
Findings land in docs/perf_notes.md; the shard_state docstring carries
the conclusion so users can decide without re-measuring.

Timing goes through the shared discipline (`madsim_tpu.measure`
via the benches/measure.py shim): fresh seeds per rep, and the warmup
compiles the EXACT (shape, SCAN) program before the timed region — an
earlier run of this table warmed with a different step count and timed
the 60-step program's XLA compile, making every cell compile-dominated
(the perf_notes §1-D caveat; the discipline is regression-pinned in
tests/test_tune.py).
"""

from __future__ import annotations

import json


def main() -> None:
    import jax
    import jax.numpy as jnp

    from madsim_tpu.tpu import BatchedSim, SimConfig, make_raft_spec

    devs = jax.devices()
    assert len(devs) >= 8, "run with xla_force_host_platform_device_count=8"

    def mesh2(n_lane, n_node):
        import numpy as np

        return jax.sharding.Mesh(
            np.array(devs[:8]).reshape(n_lane, n_node), ("seeds", "nodes")
        )

    cfg = SimConfig(
        horizon_us=60_000_000,
        loss_rate=0.1,
        crash_interval_lo_us=500_000,
        crash_interval_hi_us=3_000_000,
        restart_delay_lo_us=300_000,
        restart_delay_hi_us=2_000_000,
    )
    SCAN = 60
    for N in (8, 16, 32):
        lanes = 128
        spec = make_raft_spec(n_nodes=N, log_capacity=16, client_rate=0.1)
        sim = BatchedSim(spec, cfg)
        layouts = {
            "lanes8": (8, 1),
            "mixed2x4": (2, 4),
            "nodes8": (1, 8),
        }
        row = {"n_nodes": N, "lanes": lanes}
        for name, (nl, nn) in layouts.items():
            m = mesh2(nl, nn)

            def init(seeds, m=m, nn=nn):
                return sim.shard_state(
                    sim.init(jnp.asarray(seeds)), m, lane_axis="seeds",
                    node_axis="nodes" if nn > 1 else None,
                )

            # the shared discipline warms the EXACT (shape, SCAN)
            # program before timing (run_steps jits per (shape, n_steps);
            # a different warmup count would leave the timed call's XLA
            # compile inside the timing window) and derives fresh seeds
            # per rep. warm_steps=SCAN keeps the table's original timed
            # window: each rep settles through one SCAN chunk (initial
            # elections, log fill) and times the SECOND — steady-state
            # stepping, comparable to the perf_notes §1-D cells
            from measure import time_scan_ms

            row[name + "_step_ms"] = round(
                time_scan_ms(
                    init, sim.run_steps, lanes, scan=SCAN,
                    warm_steps=SCAN, rounds=1,
                ),
                3,
            )
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
