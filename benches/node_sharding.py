"""1-D (lanes) vs 2-D (lanes x nodes) mesh sharding, measured.

VERDICT r4 weak #4: `shard_state(node_axis=...)` existed but was
compile-tested only — no measurement of when node sharding wins or what
the cross-node gathers cost. This experiment runs the raft fuzz step on
a forced 8-device CPU mesh at growing cluster sizes and times 60-step
scans (after a 10-step warmup) under three layouts:

    lanes8   — 1-D: all 8 devices shard the lane axis (no collectives)
    mixed2x4 — 2-D: 2-way lanes x 4-way nodes
    nodes8   — node-axis only (the TP-analog extreme)

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
         python benches/node_sharding.py
Findings land in docs/perf_notes.md; the shard_state docstring carries
the conclusion so users can decide without re-measuring.
"""

from __future__ import annotations

import json
import time


def main() -> None:
    import jax
    import jax.numpy as jnp

    from madsim_tpu.tpu import BatchedSim, SimConfig, make_raft_spec

    devs = jax.devices()
    assert len(devs) >= 8, "run with xla_force_host_platform_device_count=8"

    def mesh2(n_lane, n_node):
        import numpy as np

        return jax.sharding.Mesh(
            np.array(devs[:8]).reshape(n_lane, n_node), ("seeds", "nodes")
        )

    cfg = SimConfig(
        horizon_us=60_000_000,
        loss_rate=0.1,
        crash_interval_lo_us=500_000,
        crash_interval_hi_us=3_000_000,
        restart_delay_lo_us=300_000,
        restart_delay_hi_us=2_000_000,
    )
    SCAN = 60
    for N in (8, 16, 32):
        lanes = 128
        spec = make_raft_spec(n_nodes=N, log_capacity=16, client_rate=0.1)
        sim = BatchedSim(spec, cfg)
        layouts = {
            "lanes8": (8, 1),
            "mixed2x4": (2, 4),
            "nodes8": (1, 8),
        }
        row = {"n_nodes": N, "lanes": lanes}
        for name, (nl, nn) in layouts.items():
            m = mesh2(nl, nn)
            state = sim.init(jnp.arange(lanes))
            state = sim.shard_state(
                state, m, lane_axis="seeds",
                node_axis="nodes" if nn > 1 else None,
            )
            # warmup with the SAME step count: run_steps jits per
            # (shape, n_steps), so a different warmup count would leave
            # the timed call's XLA compile inside the timing window
            state = sim.run_steps(state, SCAN)
            jax.block_until_ready(state)
            t0 = time.perf_counter()
            jax.block_until_ready(sim.run_steps(state, SCAN))
            row[name + "_step_ms"] = round(
                (time.perf_counter() - t0) / SCAN * 1e3, 3
            )
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
