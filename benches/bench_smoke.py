"""bench-smoke: a <60s-per-workload micro-bench for CI and the tier-1 tier.

The full bench.py needs a real accelerator, tens of minutes, and a quiet
machine; regressions in the SWEEP MACHINERY (eager init, per-chunk
recompiles, a dispatch storm like the r5 ~1.4 s/sweep bug) don't need any
of that to show up — they show up in the DISPATCH COUNT, which is
platform-independent and contention-proof. Each workload runs a tiny
sweep (64 lanes, ~0.6 virtual seconds) through the production run_batch
path and asserts:

  * completion with zero violations (the clean specs stay clean),
  * zero pool overflow (the zero-drop discipline at smoke scale),
  * the dispatch budget: init + one sweep segment = 2 device program
    launches per chunk, exactly (BatchResult.dispatches),
  * the LAYOUT budget (r8, docs/state_layout.md): per-workload carry
    bytes per lane (platform-independent — pure dtype x shape) and the
    bytes-per-step estimate over the carry floor. A narrowed field
    silently widening, a bool plane un-packing, or cold state leaking
    back into per-step traffic fails HERE, not three PRs later in a
    BENCH regression.

It NEVER asserts wall-clock — that is bench.py's job, on real hardware,
with the fresh-seed/median discipline. Wall times are printed for eyes
only.

Usage: python benches/bench_smoke.py  (or `make bench-smoke`)
Exit code != 0 on any assertion failure; prints one JSON line.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LANES = 64
VIRTUAL_SECS = 0.6
MAX_STEPS = 2_500  # < dispatch_steps (10k): the sweep must be ONE segment

# r8 layout budgets (docs/state_layout.md). carry_bytes_per_lane is the
# while_loop carry (hot + cold) at THIS smoke config — pure dtype x shape,
# so identical on every backend; measured values (see docs) get ~10%
# headroom for benign drift. est_over_floor bounds the step's estimated
# HBM traffic against the carry's unavoidable read+write: measured
# 3.1-4.6x on the CPU backend (TPU fuses tighter) — 6.0 catches the big
# regressions (cold state re-materializing per step costs ~+1x floor,
# donation loss ~+1x) without flaking on backend variance.
CARRY_BUDGET_B_PER_LANE = {
    "raft": 3520,
    "kv": 6880,
    "twopc": 1710,
    "paxos": 1540,
    "chain": 1670,
}
EST_OVER_FLOOR_MAX = 6.0

# r12 lineage-plane budget (docs/causality.md): with lineage=True the
# carry gains per-node Lamport clocks, the per-lane eid counter, and ONE
# u16 sent_eid stamp per pool slot — measured 3.9% (raft) to 10.3%
# (paxos, the smallest carry) at this smoke config. The 15% ceiling is
# the acceptance bar: a u32 stamp (or a second stamp plane) blows it on
# paxos/twopc, which is exactly the regression this guards. Lineage OFF
# must cost zero bytes — pinned structurally in test_state_layout.py.
LINEAGE_OVERHEAD_PCT_MAX = 15.0


def workloads():
    from madsim_tpu.tpu import chain_workload, raft_workload
    from madsim_tpu.tpu.kv import kv_workload
    from madsim_tpu.tpu.paxos import paxos_workload
    from madsim_tpu.tpu.twopc import twopc_workload

    return {
        "raft": raft_workload(virtual_secs=VIRTUAL_SECS),
        "kv": kv_workload(virtual_secs=VIRTUAL_SECS),
        "twopc": twopc_workload(virtual_secs=VIRTUAL_SECS),
        "paxos": paxos_workload(virtual_secs=VIRTUAL_SECS),
        "chain": chain_workload(virtual_secs=VIRTUAL_SECS),
    }


def layout_budget(name: str, wl) -> dict:
    """The bytes budget: carry bytes/lane (exact) + est_over_floor (XLA
    buffer-assignment estimate of the sweep-loop body vs 2x carry)."""
    import jax.numpy as jnp

    import roofline as rl
    from madsim_tpu.tpu.engine import BatchedSim

    sim = BatchedSim(wl.spec, wl.config)
    st = sim.init(jnp.arange(LANES, dtype=jnp.uint32))
    cb = rl.carry_bytes(st)
    carry = cb["hot_bytes"] + cb["cold_bytes"]
    mem = rl.mem_bytes_per_step(sim, st)
    # lineage-plane carry cost: same config, lineage=True (pure
    # dtype x shape accounting — no run, no compile)
    sim_lin = BatchedSim(wl.spec, wl.config, lineage=True)
    st_lin = sim_lin.init(jnp.arange(LANES, dtype=jnp.uint32))
    cb_lin = rl.carry_bytes(st_lin)
    carry_lin = cb_lin["hot_bytes"] + cb_lin["cold_bytes"]
    lin_pct = round(100.0 * (carry_lin - carry) / carry, 2)
    row = {
        "carry_bytes_per_lane": round(carry / LANES, 1),
        "bytes_per_step": mem["bytes_per_step"],
        "est_over_floor": round(mem["bytes_per_step"] / (2 * carry), 2),
        "lineage_carry_bytes_per_lane": round(carry_lin / LANES, 1),
        "lineage_overhead_pct": lin_pct,
    }
    errors = []
    if lin_pct > LINEAGE_OVERHEAD_PCT_MAX:
        errors.append(
            f"lineage plane widened: +{lin_pct}% carry bytes/lane > "
            f"{LINEAGE_OVERHEAD_PCT_MAX}% budget — the sent_eid stamp "
            "must stay u16 (run tests/test_state_layout.py for the "
            "field name; docs/causality.md)"
        )
    budget = CARRY_BUDGET_B_PER_LANE[name]
    if row["carry_bytes_per_lane"] > budget:
        errors.append(
            f"carry widened: {row['carry_bytes_per_lane']} B/lane > "
            f"budget {budget} — a SimState leaf grew or un-narrowed "
            "(run tests/test_state_layout.py for the field name)"
        )
    if row["est_over_floor"] > EST_OVER_FLOOR_MAX:
        errors.append(
            f"step traffic blew the floor budget: est_over_floor "
            f"{row['est_over_floor']} > {EST_OVER_FLOOR_MAX} — cold/const "
            "state re-entered the per-step carry, or donation broke"
        )
    if errors:
        row["errors"] = errors
    return row


def smoke_one(name: str, wl) -> dict:
    from madsim_tpu.tpu.batch import run_batch

    wl = dataclasses.replace(wl, max_steps=MAX_STEPS, host_repro=None)
    t0 = time.perf_counter()
    # mesh=None: a fixed single-shard layout keeps the dispatch budget
    # exact everywhere (the mesh path adds one device_put per chunk)
    res = run_batch(
        range(LANES), wl, mesh=None, max_traces=0, repro_on_host=False
    )
    wall = time.perf_counter() - t0
    row = {
        "violations": res.violations,
        "overflow": int(res.summary["total_overflow"]),
        "dispatches": res.dispatches,
        "device_ms": round(res.device_ms, 1),
        "wall_s": round(wall, 2),  # informational ONLY — never asserted
        "events": int(res.summary["total_events"]),
    }
    errors = []
    if res.violations:
        errors.append(f"{res.violations} violations on a clean spec")
    if row["overflow"]:
        errors.append(f"pool overflow {row['overflow']} at smoke scale")
    # the budget: ONE jitted init + ONE while_loop segment, nothing else.
    # An eager init is dozens of launches; a per-chunk recompile shows up
    # as timeouts; a step-granular loop would be thousands.
    if res.dispatches != 2:
        errors.append(
            f"dispatch budget blown: {res.dispatches} launches per sweep "
            "(expected 2: jitted init + one run segment)"
        )
    if row["events"] <= 0:
        errors.append("no events simulated — the sweep did nothing")
    if errors:
        row["errors"] = errors
    return row


def main() -> int:
    out = {}
    failed = False
    for name, wl in workloads().items():
        row = smoke_one(name, wl)
        row["layout"] = layout_budget(name, wl)
        out[name] = row
        errs = row.get("errors", []) + row["layout"].get("errors", [])
        failed = failed or bool(errs)
    out["ok"] = not failed
    print(json.dumps(out), flush=True)
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
