"""tune-smoke: <60 s CPU gate for the measured autotuner (ISSUE 15).

Three structural assertions, no wall-clock thresholds (wall times print
for eyes only):

  * NEVER A REGRESSION: one Tier-A coordinate pass on the 10x
    horizon-spread mix (the continuous-batching headline workload) must
    return an entry whose tuned seeds/s >= the hand-pinned default's —
    guaranteed by the tuner's final A/B guard, which falls back to the
    defaults whenever no candidate beats them; the smoke asserts the
    invariant held and that the entry round-trips through the
    `madsim-tpu-tuned/1` cache.
  * TIER-A BIT-IDENTITY: running the same admissions under the TUNED
    dispatch knobs and under the defaults yields bit-identical
    per-admission rows (violations, steps, violation steps) — the
    contract that lets `tuning="auto"` apply anywhere, even
    mid-campaign.
  * TIER-B GATE: a planted drop-inducing pool config (slot budget
    squeezed until the acceptance sweep overflows) is REJECTED by
    `tier_b_gate`, while its clean twin passes — a trajectory-affecting
    knob never reaches the cache without the zero-drop proof.

Usage: python benches/tune_smoke.py  (or `make tune-smoke`)
Exit code != 0 on any assertion failure; prints one JSON line.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LANES = 8
WAVES = 8
VIRTUAL_SECS = 0.5
MAX_STEPS = 30_000


def main() -> None:
    t0 = time.perf_counter()
    import numpy as np

    from madsim_tpu import tune
    from madsim_tpu.tpu.engine import refill_results

    failures = []
    with tempfile.TemporaryDirectory() as cache_dir:
        # -- one Tier-A coordinate pass on the spread mix ------------------
        entry = tune.tune_spread_mix(
            lanes=LANES, waves=WAVES, virtual_secs=VIRTUAL_SECS,
            max_steps=MAX_STEPS, cache_dir=cache_dir, save=True,
        )
        if entry.tuned_seeds_per_sec < entry.baseline_seeds_per_sec:
            failures.append(
                f"tuner returned a config slower than the hand-pinned "
                f"default ({entry.tuned_seeds_per_sec} < "
                f"{entry.baseline_seeds_per_sec} seeds/s) — the A/B guard "
                "must fall back, never regress"
            )
        sim, horizon = tune.spread_mix_sim(VIRTUAL_SECS)
        again = tune.load_tuned(
            "spread-mix", sim.config, LANES, dir=cache_dir
        )
        if again is None or again != entry:
            failures.append("tuned-cache round-trip did not reproduce the entry")

        # -- Tier-A bit-identity: tuned vs default dispatch knobs ----------
        A = LANES * WAVES
        ctl = tune.spread_ctl_rows(horizon, A)
        seeds = np.arange(A, dtype=np.uint32)
        from madsim_tpu.tpu.engine import DEFAULT_DISPATCH_STEPS

        default = {"refill_lanes": LANES,
                   "dispatch_steps": DEFAULT_DISPATCH_STEPS}
        tuned = {**default, **entry.dispatch}
        rows = {}
        for tag, knobs in (("default", default), ("tuned", tuned)):
            t1 = time.perf_counter()
            st = sim.run_refill(
                seeds, lanes=int(knobs["refill_lanes"]),
                max_steps=MAX_STEPS,
                dispatch_steps=int(knobs["dispatch_steps"]), ctl=ctl,
            )
            res = refill_results(st)
            rows[tag] = {
                "violated": np.asarray(res["violated"]),
                "steps": np.asarray(res["steps"]),
                "violation_step": np.asarray(res["violation_step"]),
                "wall_ms": round((time.perf_counter() - t1) * 1e3, 1),
            }
        for k in ("violated", "steps", "violation_step"):
            if not np.array_equal(rows["default"][k], rows["tuned"][k]):
                failures.append(
                    f"Tier-A bit-identity broken: per-admission {k} rows "
                    "differ between tuned and default dispatch knobs"
                )

    # -- Tier-B gate: planted dropping config vs its clean twin ------------
    from madsim_tpu.tpu import raft_workload

    wl = dataclasses.replace(
        raft_workload(virtual_secs=VIRTUAL_SECS), host_repro=None
    )
    clean = tune.tier_b_gate(wl, wl.config, seeds=48, certify=False)
    if not clean["ok"]:
        failures.append(
            f"Tier-B gate rejected the clean twin: {clean['reasons']}"
        )
    planted = dataclasses.replace(
        wl.config, msg_capacity=8, msg_depth_msg=None
    )
    bad = tune.tier_b_gate(wl, planted, seeds=48, certify=False)
    if bad["ok"]:
        failures.append(
            "Tier-B gate ACCEPTED the planted drop-inducing pool config "
            "(msg_capacity=8) — the overflow check is dead"
        )

    out = {
        "entry": entry.to_doc(),
        "bit_identity": {
            "admissions": A,
            "default_wall_ms": rows["default"]["wall_ms"],
            "tuned_wall_ms": rows["tuned"]["wall_ms"],
        },
        "tier_b_gate": {
            "clean_ok": clean["ok"],
            "planted_rejected": not bad["ok"],
            "planted_reasons": bad["reasons"][:2],
        },
        "wall_s": round(time.perf_counter() - t0, 1),
        "failures": failures,
    }
    print(json.dumps(out), flush=True)
    if failures:
        raise SystemExit("TUNE-SMOKE RED: " + "; ".join(failures))


if __name__ == "__main__":
    main()
