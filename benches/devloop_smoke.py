"""devloop-smoke: <60s device-resident-search gate for CI (r19).

The device-resident generation loop's value proposition is dispatch
economics, so this smoke asserts the hardware-independent numbers on the
planted raft re-stamp config (the same search run both ways on one
shared sim — benches/explore_bench.devloop_ab):

  * BIT-IDENTITY: the device-loop report fingerprints identically to the
    host loop — corpus, curves, violations (the determinism contract at
    smoke scale; the full matrix lives in tests/test_devloop.py);
  * the SYNC BUDGET: the device loop blocks on the device ONCE PER
    WINDOW (`devloop_results`), so syncs/generation <= 1 — vs the host
    loop's one blocking decode plus upload round-trips every generation;
  * the DISPATCH BUDGET: whole windows run as one dispatch chain, so the
    device loop's total dispatch count (init + segments + early-stop
    reductions) lands strictly below the host loop's for the same
    generations.

Wall times (generations/s) are printed for eyes only — on CPU the sync
savings are noise; on a tunneled TPU they are the whole point
(docs/perf_notes.md r19). Usage:
python benches/devloop_smoke.py  (or `make devloop-smoke`)
Exit code != 0 on any assertion failure; prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LANES = 16
GENS = 4
WINDOW = 2


def main() -> None:
    t0 = time.perf_counter()
    import explore_bench
    import ttfb

    factory, _ = ttfb.PLANTED["raft_restamp"]
    row = explore_bench.devloop_ab(
        factory(), lanes=LANES, gens=GENS, window=WINDOW,
    )

    failures = []
    if not row["fingerprint_match"]:
        failures.append(
            "device-loop report fingerprint differs from the host loop "
            "— the determinism contract is broken"
        )
    if row["device"]["syncs_per_gen"] > 1.0:
        failures.append(
            f"device loop blocked {row['device']['syncs']} times for "
            f"{GENS} generations (budget: 1/window = "
            f"{GENS // WINDOW}) — a host round-trip leaked into the "
            "generation boundary?"
        )
    if row["device"]["syncs"] != (GENS + WINDOW - 1) // WINDOW:
        failures.append(
            f"device loop synced {row['device']['syncs']} times, "
            f"expected one per window ({(GENS + WINDOW - 1) // WINDOW})"
        )
    if row["host"]["syncs"] != GENS:
        failures.append(
            f"host loop decoded {row['host']['syncs']} times for "
            f"{GENS} generations — the baseline moved, re-pin the smoke"
        )
    if row["device"]["dispatches"] >= row["host"]["dispatches"]:
        failures.append(
            f"device loop cost {row['device']['dispatches']} dispatches "
            f">= host loop's {row['host']['dispatches']} — the in-jit "
            "boundary is not amortizing"
        )

    out = {
        "devloop": row,
        "wall_s": round(time.perf_counter() - t0, 1),
        "ok": not failures,
        "failures": failures,
    }
    print(json.dumps(out), flush=True)
    if failures:
        sys.exit(1)


if __name__ == "__main__":
    main()
