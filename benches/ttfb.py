"""Time-to-first-bug: the product metric, finally measured.

Seeds/sec is a proxy; the currency a DST user actually spends is
WALL-CLOCK from "I typed the command" to "I hold a confirmed, shrunk,
replayable violation" (BASELINE.json's `metric` names both halves; the
FoundationDB-style argument in PAPER.md is about this number, and the
fuzzing literature budgets the same way — libFuzzer/AFL count wall time
to first crash, not execs/s in isolation).

The harness sweeps PLANTED-BUG configs already in-tree — bugs this
framework's own fuzz found or the canonical wrong implementations its
tests inject — from a COLD runtime: the clock starts before the first
compile, because the user's does too. Reported per config:

    compile+first-chunk overhead   (cold start to first decoded chunk)
    wall_to_first_violation_s      (cold start to a confirmed violating seed)
    wall_to_bundle_s               (... to a finished triage ReproBundle)
    seeds_swept / violating_seed / shrink dispatch count

Usage: python benches/ttfb.py [--chunk 1024] [--max-seeds 8192]
Prints one JSON line; bench.py embeds the same rows in BENCH as `ttfb`.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import tempfile
import time


def _repo_root_on_path() -> None:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)


_repo_root_on_path()


def _restamp_plan():
    """The restamp config's FaultPlan — shared verbatim by the device leg
    (compile_plan) and the schedule-matched host leg (NemesisDriver), so
    both backends execute the SAME per-seed fault stream."""
    from madsim_tpu.nemesis import Crash, FaultPlan, Partition

    return FaultPlan(name="ttfb-restamp", clauses=(
        Crash(interval_lo_us=400_000, interval_hi_us=1_500_000,
              down_lo_us=300_000, down_hi_us=1_000_000),
        Partition(interval_lo_us=300_000, interval_hi_us=1_200_000,
                  heal_lo_us=400_000, heal_hi_us=1_500_000),
    ))


def restamp_workload():
    """The deposed-leader re-stamp bug (docs/bugs_found.md #1, the round-2
    trophy: a deposed leader re-stamps its stale log tail with the newly
    adopted term) under a schedule-clause fault plan — crash/restart +
    partition windows force the elections that expose it, and give the
    shrinker real occurrence atoms to drop."""
    import jax.numpy as jnp

    from madsim_tpu.tpu import SimConfig, make_raft_spec, raft_workload
    from madsim_tpu.tpu import nemesis as tn
    from madsim_tpu.tpu import raft as raft_mod
    from madsim_tpu.tpu.spec import replace_handlers

    spec = make_raft_spec(5, client_rate=0.8)

    def buggy_on_message(s, nid, src, kind, payload, now, key):
        state, out, timer = spec.on_message(s, nid, src, kind, payload, now, key)
        deposed = (s.role == raft_mod.LEADER) & (state.role != raft_mod.LEADER)
        log_idx = jnp.arange(s.log_term.shape[0], dtype=jnp.int32)
        in_log = log_idx < state.log_len
        log_term = jnp.where(deposed & in_log, state.term, state.log_term)
        return state._replace(log_term=log_term), out, timer

    cfg = tn.compile_plan(
        _restamp_plan(), SimConfig(horizon_us=5_000_000, loss_rate=0.0)
    )
    wl = raft_workload(spec=replace_handlers(spec, on_message=buggy_on_message))
    return dataclasses.replace(wl, config=cfg, host_repro=None)


def chain_straggler_workload():
    """The chain-replication blind-apply bug under heavy-tail stragglers:
    a replica missing the apply-if-newer guard is only exposed when a
    seconds-late duplicate forward overtakes a newer write — the buggify
    tail's signature bug class (tests/test_tpu_chain.py plants the same
    pair)."""
    from madsim_tpu.tpu import chain_workload
    from madsim_tpu.tpu.chain import make_chain_spec

    wl = chain_workload(virtual_secs=8.0)
    cfg = dataclasses.replace(
        wl.config, buggify_delay_rate=0.05, buggify_depth=8
    )
    return dataclasses.replace(
        wl, spec=make_chain_spec(5, buggy_blind_apply=True), config=cfg,
        host_repro=None,
    )


def _host_raft_restamp(seed: int, schedule_matched: bool = True) -> bool:
    """One host-runtime seed of the same planted bug class (the host
    twin's `buggy=True` is the deposed-leader re-stamp injection) —
    True when the seed violates.

    Schedule-matched by default: the host consumes the SAME compiled
    per-seed `_restamp_plan()` stream through `NemesisDriver` that the
    device executes (docs/oracle.md), so the A/B is controlled — horizon
    5 s, client_rate 0.8, loss 0.0, identical crash/partition windows.
    `schedule_matched=False` restores the legacy host-native chaos
    distributions (indicative only)."""
    from madsim_tpu.workloads import raft_host

    plan = _restamp_plan() if schedule_matched else None
    try:
        raft_host.fuzz_one_seed(
            seed, virtual_secs=5.0, loss_rate=0.0,
            chaos=not schedule_matched, buggy=True, client_rate=0.8,
            partitions=not schedule_matched, plan=plan,
        )
        return False
    except raft_host.InvariantViolation:
        return True


def _straggler_plan():
    """chain_workload's legacy crash knobs as a FaultPlan: identical
    interval/down distributions, but compiled to the pure per-seed
    schedule so the host leg drives them through NemesisDriver."""
    from madsim_tpu.nemesis import Crash, FaultPlan

    return FaultPlan(name="ttfb-straggler", clauses=(
        Crash(interval_lo_us=400_000, interval_hi_us=2_000_000,
              down_lo_us=200_000, down_hi_us=1_000_000),
    ))


def _host_chain_straggler(seed: int, schedule_matched: bool = True) -> bool:
    """Schedule-matched by default: crash windows come from the compiled
    `_straggler_plan()` stream (horizon 8 s, loss 0.1). The buggify
    straggler TAIL stays host-native in both modes — it is a runtime
    knob, not a FaultPlan clause, so it has no pure-schedule face (the
    remaining uncontrolled surface; docs/oracle.md documents the
    boundary). `schedule_matched=False` restores the legacy host-native
    crash task as well."""
    from madsim_tpu.workloads import chain_host

    plan = _straggler_plan() if schedule_matched else None
    try:
        chain_host.fuzz_one_seed(
            seed, virtual_secs=8.0, chaos=not schedule_matched, tails=True,
            buggy=True, plan=plan,
        )
        return False
    except chain_host.InvariantViolation:
        return True


def measure_host_ttfb(run_seed, max_seeds: int = 4096,
                      deadline_s: float = 180.0) -> dict:
    """The CPU comparator (BASELINE.json's metric says 'time-to-first-bug
    VS CPU'): sweep seeds one at a time on the host runtime — the
    reference's thread-per-seed execution model, one core — until the
    first violation or the wall deadline."""
    t0 = time.perf_counter()
    for seed in range(max_seeds):
        hit = run_seed(seed)
        if hit:
            return {
                "found": True,
                "violating_seed": seed,
                "seeds_swept": seed + 1,
                "wall_to_first_violation_s": round(
                    time.perf_counter() - t0, 3
                ),
            }
        if time.perf_counter() - t0 > deadline_s:
            return {
                "found": False,
                "seeds_swept": seed + 1,
                "gave_up_after_s": round(time.perf_counter() - t0, 3),
            }
    return {
        "found": False,
        "seeds_swept": max_seeds,
        "gave_up_after_s": round(time.perf_counter() - t0, 3),
    }


PLANTED = {
    "raft_restamp": (restamp_workload, _host_raft_restamp),
    "chain_straggler": (chain_straggler_workload, _host_chain_straggler),
}


def measure_ttfb(
    workload, chunk: "int | None" = None, max_seeds: int = 8192,
    shrink: bool = True, out_dir: "str | None" = None,
    lane_width: int = 16, refill: int = 0, tuning=None,
) -> dict:
    """Sweep seeds in chunks from a COLD runtime until the first violation,
    then shrink it to a ReproBundle. The chunk loop is double-buffered like
    run_batch's (chunk k+1 in flight while chunk k's violation scalars are
    decoded), and every wall-clock number includes everything the user
    would wait for — compiles included.

    `refill=<lanes>` sweeps each chunk continuously batched instead
    (engine.run_refill): lanes retiring at first violation immediately
    admit the next seed, so the chip spends no time running doomed-lane
    tails to the horizon. The first violation is identified and
    TIMESTAMPED from the retired admission's own harvested row — its
    `violation_step` and virtual `violation_t_us` — in admission order,
    NEVER from the segment-end state (a refill segment retires hundreds
    of admissions before the host sees anything; the row is the only
    honest per-admission clock). ttfb(refill) therefore reports the SAME
    violating seed, violation_step and violation_t_us as the chunked
    sweep (pinned by tests/test_refill.py), with wall-clock the only
    thing that moves."""
    import numpy as np

    from madsim_tpu import triage
    from madsim_tpu.tpu.batch import pipelined
    from madsim_tpu.tpu.engine import BatchedSim, refill_results
    from madsim_tpu.tpu.spec import REBASE_US

    if tuning is not None and chunk is None:
        # Tier-A, CHUNK ONLY (docs/tuning.md): ttfb's headline is defined
        # as a chunked-vs-refill A/B, so a tuned entry may resize the
        # chunk (where the caller kept the default) but must never flip
        # which path a row measures — tuned refill_lanes is deliberately
        # NOT applied here. An explicit chunk skips the lookup entirely:
        # the cache could not affect the sweep, so a bad entry must not
        # be able to abort it either.
        from madsim_tpu import tune as _tune
        from madsim_tpu.tpu.spec import SimConfig

        # resolve at the SWEEP scale (max_seeds), matching run_batch's
        # seeds_arr.size convention — the lane bucket is the scale of
        # the whole sweep, not of one chunk. config normalized like
        # every other consumer: None hashes as the default SimConfig()
        # the engine would run, so all entry points compute one key.
        tn = _tune.resolve_tuning(
            tuning, workload.spec.name, workload.config or SimConfig(),
            max_seeds,
        )
        if tn.get("chunk") and chunk is None:
            chunk = int(tn["chunk"])
    if chunk is None:
        chunk = 1024

    t0 = time.perf_counter()
    sim = BatchedSim(workload.spec, workload.config)
    first_violation: dict = {}

    def dispatch(lo: int):
        seeds = np.arange(lo, lo + chunk, dtype=np.uint32)
        if refill:
            # ONE segment, like the chunked branch below: total_steps ==
            # dispatch_steps keeps the engine's inter-segment early-stop
            # reduction out of dispatch(), so the refill segment is
            # launched without blocking the host and chunk k+1 really is
            # in flight while chunk k decodes. The bound is generous
            # (every admission's full per-admission budget in sequence
            # would fit twice over) and the while_loop exits when the
            # queue drains regardless.
            total = workload.max_steps * ((-(-chunk // refill)) + 1) * 2
            return seeds, sim.run_refill(
                seeds, lanes=refill, max_steps=workload.max_steps,
                total_steps=total, dispatch_steps=total,
            )
        # ONE segment per chunk (dispatch_steps == max_steps): the engine's
        # multi-segment early-stop blocks the host on an inter-segment
        # reduction, which would delay decode(k) — and the violation
        # timestamp — until chunk k+1 was nearly done. A single segment
        # makes dispatch truly non-blocking, so time-to-first-violation is
        # the data-ready time, not an artifact of the chunking. (The lanes
        # still stop early on device: the while_loop exits when every lane
        # is done.)
        return seeds, sim.run(
            seeds, max_steps=workload.max_steps,
            dispatch_steps=workload.max_steps,
        )

    first_chunk_s = None
    found = None
    swept = 0

    def decode(entry):
        nonlocal first_chunk_s, swept
        seeds, st = entry
        if refill:
            res = refill_results(st)
            violated = res["violated"]
        else:
            res = None
            violated = np.asarray(st.violated)
        swept += seeds.size
        if first_chunk_s is None:
            first_chunk_s = time.perf_counter() - t0
        if violated.any():
            i = int(np.nonzero(violated)[0][0])  # admission order
            if refill:
                vs = int(res["violation_step"][i])
                vt = int(res["violation_epoch"][i]) * REBASE_US + int(
                    res["violation_at"][i]
                )
            else:
                vs = int(np.asarray(st.violation_step)[i])
                vt = int(np.asarray(st.violation_epoch)[i]) * REBASE_US + (
                    int(np.asarray(st.violation_at)[i])
                )
            first_violation.update(
                violation_step=vs, violation_t_us=vt,
            )
            return int(seeds[i])
        return None

    # double-buffered: chunk k+1 is in flight while chunk k's violation
    # bits are decoded (a hit mid-pipeline wastes the in-flight chunk —
    # the price of the overlap, and far cheaper than serializing)
    found = pipelined(range(0, max_seeds, chunk), dispatch, decode)
    out = {
        "chunk": chunk,
        "seeds_swept": swept,
        "first_chunk_s": round(first_chunk_s or 0.0, 3),
    }
    if refill:
        out["refill_lanes"] = refill
    if found is None:
        out["found"] = False
        out["wall_to_first_violation_s"] = None
        return out
    t_first = time.perf_counter() - t0
    out.update({
        "found": True,
        "violating_seed": found,
        "wall_to_first_violation_s": round(t_first, 3),
        # the admission's own record of WHEN it violated (virtual time /
        # step index) — identical between the refill and chunked sweeps
        # for the same seed (per-admission bit-identity)
        **first_violation,
    })
    if shrink:
        own_tmp = None
        if out_dir is None:
            own_tmp = tempfile.mkdtemp(prefix="ttfb_bundles_")
            out_dir = own_tmp
        try:
            sr = triage.shrink_seed(
                workload, found, out_dir=out_dir, lane_width=lane_width,
            )
            out.update({
                "wall_to_bundle_s": round(time.perf_counter() - t0, 3),
                "shrink_dispatches": sr.dispatches,
                "atoms": f"{sr.original_atoms}->{len(sr.kept_atoms)}",
                "bundle_path": sr.bundle_path,
            })
        except Exception as e:  # noqa: BLE001 - report, don't kill the bench
            out["shrink_error"] = f"{type(e).__name__}: {str(e)[:160]}"
    return out


def ttfb_all(chunk: "int | None" = None, max_seeds: int = 8192,
             shrink: bool = True, host_baseline: bool = True,
             host_deadline_s: float = 180.0, refill: int = 64,
             tuning=None, host_schedule_matched: bool = True) -> dict:
    rows = {}
    for name, (factory, host_fn) in PLANTED.items():
        try:
            row = measure_ttfb(
                factory(), chunk=chunk, max_seeds=max_seeds, shrink=shrink,
                tuning=tuning,
            )
        except Exception as e:  # noqa: BLE001 - one bad config must not
            # hide the other's number
            row = {"error": f"{type(e).__name__}: {str(e)[:160]}"}
        if refill:
            # the continuously batched sweep of the same config (cold
            # runtime again): must identify the SAME violation (seed /
            # step / virtual time); only wall-clock may move. Same
            # `tuning` as the chunked leg — measure_ttfb applies chunk
            # only, so both legs run the same chunk size and the A/B
            # isolates the refill-vs-chunked effect.
            try:
                r2 = measure_ttfb(
                    factory(), chunk=chunk, max_seeds=max_seeds,
                    shrink=False, refill=refill, tuning=tuning,
                )
                row["refill"] = {
                    k: r2.get(k) for k in (
                        "refill_lanes", "found", "seeds_swept",
                        "first_chunk_s", "wall_to_first_violation_s",
                        "violating_seed", "violation_step",
                        "violation_t_us",
                    )
                }
            except Exception as e:  # noqa: BLE001
                row["refill"] = {
                    "error": f"{type(e).__name__}: {str(e)[:160]}"
                }
        if host_baseline and host_fn is not None:
            try:
                host = measure_host_ttfb(
                    lambda s: host_fn(
                        s, schedule_matched=host_schedule_matched
                    ),
                    deadline_s=host_deadline_s,
                )
                host["schedule_matched"] = host_schedule_matched
                row["host"] = host
                dev = row.get("wall_to_first_violation_s")
                if dev and host.get("wall_to_first_violation_s"):
                    # a controlled A/B by default: the host leg consumes
                    # the SAME compiled per-seed FaultPlan stream through
                    # NemesisDriver that the device executes, verified
                    # draw-for-draw by the standing differential oracle
                    # (madsim_tpu/oracle.py, docs/oracle.md). The legacy
                    # host-native distributions (indicative only) are
                    # behind --host-legacy / host_schedule_matched=False.
                    row["vs_host"] = round(
                        host["wall_to_first_violation_s"] / dev, 2
                    )
            except Exception as e:  # noqa: BLE001
                row["host"] = {"error": f"{type(e).__name__}: {str(e)[:160]}"}
        rows[name] = row
    return rows


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--chunk", type=int, default=None,
        help="seeds per dispatch (default 1024; omit to let a tuned "
        "cache entry resize it when tuning is wired through)",
    )
    parser.add_argument("--max-seeds", type=int, default=8192)
    parser.add_argument("--no-shrink", action="store_true")
    parser.add_argument("--no-host", action="store_true")
    parser.add_argument("--host-deadline", type=float, default=180.0)
    parser.add_argument(
        "--host-legacy", action="store_true",
        help="host leg rolls its legacy host-native fault distributions "
        "instead of the schedule-matched compiled FaultPlan stream "
        "(indicative only — the default is a controlled A/B, "
        "docs/oracle.md)",
    )
    parser.add_argument(
        "--refill", type=int, default=64, metavar="LANES",
        help="also sweep each config continuously batched over LANES "
        "lanes (0 disables)",
    )
    parser.add_argument(
        "--tuning", default=None, metavar="AUTO|PATH",
        help="consult the tuned-config cache ('auto') or a saved entry "
        "for the sweep chunk — chunk only, applied to BOTH A/B legs "
        "(docs/tuning.md); default: the hand-pinned 1024",
    )
    args = parser.parse_args()
    print(
        json.dumps(ttfb_all(
            args.chunk, args.max_seeds, shrink=not args.no_shrink,
            host_baseline=not args.no_host,
            host_deadline_s=args.host_deadline, refill=args.refill,
            tuning=args.tuning,
            host_schedule_matched=not args.host_legacy,
        )),
        flush=True,
    )


if __name__ == "__main__":
    main()
