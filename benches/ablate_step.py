"""Ablation attribution of the step cost: time the full step, then steps
with one phase neutralized. Deltas rank where the milliseconds go.

Methodology: the shared measurement discipline (`madsim_tpu.measure`,
via the benches/measure.py shim) — on-device lax.scan chunks (per-step
host dispatch costs ms over the tunnel and drowns the signal), fresh
seeds derived per rep index (the tunnel relay caches identical
dispatches), exact-program warmup, medians over rounds (the chip is
shared and contention is bursty).

Usage: PYTHONPATH=... python benches/ablate_step.py [--lanes 32768]
"""

from __future__ import annotations

import argparse
import dataclasses
import json

SCAN = 300


def measure(sim, lanes, rounds, warm_steps=200):
    """Median ms/step over `rounds` fresh-seed reps of a SCAN-step chunk
    (the shared discipline: measure.time_scan_ms)."""
    from measure import time_scan_ms

    return time_scan_ms(
        sim.init, sim.run_steps, lanes, scan=SCAN, warm_steps=warm_steps,
        rounds=rounds,
    )


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--lanes", type=int, default=32768)
    parser.add_argument("--rounds", type=int, default=3)
    args = parser.parse_args()

    import jax.numpy as jnp

    from madsim_tpu.tpu import BatchedSim, SimConfig, make_raft_spec
    from madsim_tpu.tpu.spec import Outbox

    def make(cfg_over=None, spec_over=None):
        spec = make_raft_spec(n_nodes=5, client_rate=0.1)
        if spec_over:
            from madsim_tpu.tpu.spec import replace_handlers

            spec = replace_handlers(spec, **spec_over)
        kw = dict(
            horizon_us=10_000_000,
            msg_capacity=128,
            loss_rate=0.10,
            crash_interval_lo_us=500_000,
            crash_interval_hi_us=3_000_000,
            restart_delay_lo_us=300_000,
            restart_delay_hi_us=2_000_000,
            partition_interval_lo_us=300_000,
            partition_interval_hi_us=1_500_000,
            partition_heal_lo_us=500_000,
            partition_heal_hi_us=2_000_000,
        )
        kw.update(cfg_over or {})
        return BatchedSim(spec, SimConfig(**kw))

    spec0 = make_raft_spec(n_nodes=5, client_rate=0.1)

    def id_on_message(s, nid, src, kind, payload, now, key):
        E = spec0.max_out_msg
        out = Outbox(
            valid=jnp.zeros((E,), jnp.bool_),
            dst=jnp.zeros((E,), jnp.int32),
            kind=jnp.zeros((E,), jnp.int32),
            payload=jnp.zeros((E, spec0.payload_width), jnp.int32),
        )
        return s, out, jnp.int32(-1)

    def id_on_timer(s, nid, now, key):
        E = spec0.max_out
        out = Outbox(
            valid=jnp.zeros((E,), jnp.bool_),
            dst=jnp.zeros((E,), jnp.int32),
            kind=jnp.zeros((E,), jnp.int32),
            payload=jnp.zeros((E, spec0.payload_width), jnp.int32),
        )
        return s, out, now + 50_000

    variants = {
        "full": make(),
        "no_invariants": make(
            spec_over={"check_invariants": lambda ns, alive, now: jnp.bool_(True)}
        ),
        "id_on_message": make(spec_over={"on_message": id_on_message}),
        "id_on_timer": make(spec_over={"on_timer": id_on_timer}),
        "id_both_handlers": make(
            spec_over={"on_message": id_on_message, "on_timer": id_on_timer}
        ),
        "no_chaos": make(
            cfg_over={"crash_interval_lo_us": 0, "crash_interval_hi_us": 0,
                      "partition_interval_lo_us": 0,
                      "partition_interval_hi_us": 0}
        ),
        "depth2": make(cfg_over={"msg_capacity": 300}),
    }

    med = {}
    for name, sim in variants.items():
        med[name] = measure(sim, args.lanes, args.rounds)
        print(
            json.dumps({
                "variant": name,
                "ms_per_step": round(med[name], 3),
                "delta_ms": round(med["full"] - med[name], 3),
            }),
            flush=True,
        )


if __name__ == "__main__":
    main()
