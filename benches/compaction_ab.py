"""compaction-ab: the r8 layout change, A/B'd structurally in under 60 s.

Two equivalences, each asserted BIT-FOR-BIT on a small lane count (the
golden-digest / layout-lint suites carry the same contracts as tests;
this target is the one-command developer check after touching the
engine's carry):

  serial-vs-donated   the production donated, hot/cold/const-split sweep
                      (`_run` + while_loop) against an undonated
                      step-at-a-time scan over the FLAT SimState — the
                      r7-shaped loop. Donation and the carry split are
                      executor-level restructurings; one diverging leaf
                      means a buffer was clobbered or a const leaked.

  packed-vs-unpacked  the compacted layout against BOTH unpacked
                      references: (a) the same spec with dtype narrowing
                      STRIPPED, canonical trajectories bit-equal (plane
                      packing is unconditional, so this leg isolates
                      narrowing); (b) the canonical golden digest of the
                      packed engine against the constant RECORDED FROM
                      the pre-compaction r7 engine (unpacked bool
                      planes, flat i32 node state) — the cross-version
                      witness that packing itself changed nothing
                      (tests/test_state_layout.py pins the same
                      constants; this target replays the raft one).

Wall-clock is printed for eyes but never asserted (bench.py's job, on
real hardware). Exit code != 0 on any mismatch.

Usage: python benches/compaction_ab.py  (or `make compaction-ab`)
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LANES = 48
STEPS = 1_200


def _chaos_cfg():
    from madsim_tpu import nemesis
    from madsim_tpu.tpu import nemesis as tpu_nemesis
    from madsim_tpu.tpu.spec import SimConfig

    plan = nemesis.FaultPlan(
        name="compaction-ab",
        clauses=(
            nemesis.Crash(interval_lo_us=300_000, interval_hi_us=900_000,
                          down_lo_us=200_000, down_hi_us=600_000),
            nemesis.Partition(
                interval_lo_us=400_000, interval_hi_us=1_200_000,
                heal_lo_us=300_000, heal_hi_us=900_000,
            ),
            nemesis.MsgLoss(rate=0.05),
        ),
    )
    return tpu_nemesis.compile_plan(plan, SimConfig(horizon_us=30_000_000))


def _leaf_mismatches(a, b, widen=None):
    """Names of leaves that differ between two final states (canonical:
    node widened, packed planes compared as stored words)."""
    import jax
    import numpy as np

    bad = []
    na = widen(a.node) if widen else a.node
    nb = widen(b.node) if widen else b.node
    for f, x, y in zip(
        type(na)._fields, jax.tree_util.tree_leaves(na),
        jax.tree_util.tree_leaves(nb),
    ):
        if not np.array_equal(np.asarray(x), np.asarray(y)):
            bad.append(f"node.{f}")
    for f in ("clock", "epoch", "key", "done", "violated", "steps",
              "events", "overflow", "dead_drops", "fires", "alive_p",
              "crashed", "chaos_at", "link_ok_p", "partitioned", "part_at",
              "timer"):
        if not np.array_equal(
            np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        ):
            bad.append(f)
    for f in ("deliver", "kind", "payload"):
        x = np.asarray(getattr(a.msgs, f)).astype(np.int64)
        y = np.asarray(getattr(b.msgs, f)).astype(np.int64)
        if not np.array_equal(x, y):
            bad.append(f"msgs.{f}")
    import numpy as _np
    if not _np.array_equal(
        _np.asarray(a.msgs.valid), _np.asarray(b.msgs.valid)
    ):
        bad.append("msgs.valid")
    return bad


def serial_vs_donated(spec, cfg) -> dict:
    """Production donated split sweep == undonated flat serial scan."""
    import functools

    import jax
    import jax.numpy as jnp

    from madsim_tpu.tpu.engine import BatchedSim

    sim = BatchedSim(spec, cfg)
    seeds = jnp.arange(LANES, dtype=jnp.uint32)

    t0 = time.perf_counter()
    donated = sim.run(seeds, max_steps=STEPS, dispatch_steps=STEPS)
    wall_don = time.perf_counter() - t0

    # the r7-shaped reference loop: flat SimState carry, no donation, no
    # hot/cold/const split — every step re-emits the whole pytree
    @functools.partial(jax.jit, static_argnums=(0,))
    def serial_run(n_steps, state):
        def body(s, _):
            return sim._step(s), None

        final, _ = jax.lax.scan(body, state, None, length=n_steps)
        return final

    t0 = time.perf_counter()
    state0 = sim.init(seeds)
    # mirror run()'s early-exit semantics at this scale: STEPS < horizon
    # exit for these configs, so a fixed-length scan matches while_loop
    serial = serial_run(STEPS, state0)
    wall_ser = time.perf_counter() - t0

    bad = _leaf_mismatches(donated, serial)
    return {
        "wall_donated_s": round(wall_don, 2),
        "wall_serial_s": round(wall_ser, 2),
        "mismatched_leaves": bad,
    }


def packed_vs_unpacked(spec, cfg) -> dict:
    """Compacted spec == unpacked references: (a) narrowing stripped,
    canonical trajectories bit-equal; (b) the pinned r7 (unpacked-engine)
    golden digest reproduced by the packed engine."""
    import jax.numpy as jnp

    from madsim_tpu.tpu.engine import BatchedSim

    seeds = jnp.arange(LANES, dtype=jnp.uint32)
    wide = dataclasses.replace(spec, narrow_fields=None)
    simN, simW = BatchedSim(spec, cfg), BatchedSim(wide, cfg)
    t0 = time.perf_counter()
    stN = simN.run(seeds, max_steps=STEPS, dispatch_steps=STEPS)
    wall_n = time.perf_counter() - t0
    t0 = time.perf_counter()
    stW = simW.run(seeds, max_steps=STEPS, dispatch_steps=STEPS)
    wall_w = time.perf_counter() - t0
    bad = _leaf_mismatches(stN, stW, widen=simN._widen_node)

    # (b) the cross-version packing witness: today's packed engine must
    # reproduce the canonical digest RECORDED FROM the r7 engine, whose
    # planes were unpacked bools and whose node state was flat i32 —
    # plane packing cannot hide behind itself here
    from tests.test_state_layout import GOLDEN, _golden_one

    golden_ok = True
    try:
        _golden_one("raft")
    except AssertionError:
        golden_ok = False
        bad = bad + ["r7-golden-digest(raft)"]
    return {
        "wall_packed_s": round(wall_n, 2),
        "wall_wide_s": round(wall_w, 2),
        "r7_unpacked_golden_ok": golden_ok,
        "golden_workloads_pinned": len(GOLDEN),
        "mismatched_leaves": bad,
    }


def main() -> int:
    from madsim_tpu.tpu.raft import make_raft_spec

    cfg = _chaos_cfg()
    spec = make_raft_spec()
    out = {
        "lanes": LANES,
        "steps": STEPS,
        "serial_vs_donated": serial_vs_donated(spec, cfg),
        "packed_vs_unpacked": packed_vs_unpacked(spec, cfg),
    }
    ok = not (
        out["serial_vs_donated"]["mismatched_leaves"]
        or out["packed_vs_unpacked"]["mismatched_leaves"]
    )
    out["ok"] = ok
    print(json.dumps(out), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
