"""Shim: the shared measurement discipline lives in `madsim_tpu.measure`
(fresh-seed reps, exact-program warmup, interleaved-round medians,
scan-on-device timing) so the package — notably the `madsim_tpu.tune`
autotuner — can import it without sys.path tricks; the benches import it
from here by its historical name. One implementation, two doors."""

from madsim_tpu.measure import (  # noqa: F401 - re-exported surface
    SweepTimer,
    fresh_seeds,
    interleaved_medians,
    median,
    time_scan_ms,
    time_sweep,
)
