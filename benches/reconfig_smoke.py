"""reconfig-smoke: <60s membership-axis gate for CI.

The r17 reconfig clause's pitch is that dynamic membership is a fault
AXIS, not scenery: a bug class REACHABLE ONLY through remove/join churn
must flow through the whole farm — explorer, ddmin, campaign dedup,
causal anatomy — and come out the other side named. This smoke walks
that path on the planted kafka-family ISR bug (a wipe-joined replica
re-enters the ISR without catch-up, `make_isr_spec(buggy_stale_isr=
True)`) under a reconfig-ONLY plan — no crash clauses, loss pinned low —
so the shrunk minimal plan can only ever blame the membership axis:

  * FIND: one explorer generation over the planted config surfaces the
    bug on multiple fresh seeds (the bug is seed-dense under churn, the
    regime campaign dedup exists for);
  * SHRINK: the campaign ddmin-shrinks the first witness and the kept
    minimal plan names `reconfig` occurrence atoms (crash cannot appear:
    the plan has none to keep);
  * DEDUP: every further violating seed attaches as a witness of ONE
    BugRecord — one bug class, one record, a saved ReproBundle;
  * ANATOMY: the r12 cross-witness skeleton names the reconfig delivery
    mechanism — the FETCH delivery from the rejoined replica that the
    stale-ISR leader admits without catch-up;
  * CONTROL: the correct spec stays silent under the exact same churn.

Wall times are printed for eyes only. Usage:
python benches/reconfig_smoke.py  (or `make reconfig-smoke`)
Exit code != 0 on any assertion failure; prints one JSON line.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LANES = 32
VIRTUAL_SECS = 6.0


def reconfig_only_workload(buggy: bool = True):
    """The planted ISR config with membership churn as the ONLY schedule
    clause (loss stays as low message noise). `isr_workload` proper runs
    crash + reconfig together; this bench isolates the axis so ddmin's
    verdict is unambiguous."""
    from madsim_tpu.tpu.batch import BatchWorkload
    from madsim_tpu.tpu.isr import make_isr_spec
    from madsim_tpu.tpu.spec import SimConfig, pool_kw_for

    spec = make_isr_spec(5, buggy_stale_isr=buggy)
    cfg = SimConfig(
        horizon_us=int(VIRTUAL_SECS * 1e6),
        **pool_kw_for(
            spec,
            fused=dict(msg_depth_msg=2, msg_spare_slots=2),
            two_handler=dict(msg_depth_msg=2, msg_depth_timer=2),
        ),
        loss_rate=0.05,
        nem_reconfig_interval_lo_us=600_000,
        nem_reconfig_interval_hi_us=1_800_000,
        # down windows above repl_timeout_us so eviction precedes rejoin
        nem_reconfig_down_lo_us=300_000,
        nem_reconfig_down_hi_us=900_000,
    )
    return BatchWorkload(spec=spec, config=cfg)


def main() -> None:
    t0 = time.perf_counter()
    import jax.numpy as jnp
    import numpy as np

    from madsim_tpu import campaign
    from madsim_tpu.tpu.engine import BatchedSim

    wl = reconfig_only_workload(buggy=True)
    sim = BatchedSim(wl.spec, wl.config, triage=True, coverage=True)
    root = tempfile.mkdtemp(prefix="reconfig_smoke_")
    try:
        # -- find + shrink + dedup: one campaign generation -------------
        camp = campaign.Campaign(
            wl, os.path.join(root, "c"), meta_seed=0, lanes=LANES,
            shrink=True, max_shrinks=2, sim=sim,
            anatomy=True, max_anatomy_witnesses=2,
        )
        rep = camp.run(1)
        t_campaign = time.perf_counter() - t0
        n_viol = len(camp.ex.violations)
        assert n_viol >= 2, (
            f"planted ISR bug found on only {n_viol} candidates — "
            "membership churn is not reaching the stale-ISR admission"
        )

        # -- dedup: one bug class, ONE record ---------------------------
        assert len(camp.bugs) == 1, (
            f"one planted bug must dedup to one BugRecord, got "
            f"{len(camp.bugs)}: "
            f"{[(b.signature[:12], b.violation_kind) for b in camp.bugs]}"
        )
        bug = camp.bugs[0]
        assert bug.shrink_error is None, f"shrink failed: {bug.shrink_error}"
        assert len(bug.witnesses) >= 2, (
            f"seed-dense bug attached only {len(bug.witnesses)} witnesses"
        )

        # -- shrink: the minimal plan blames the membership axis --------
        profile = dict((n, c) for n, c in bug.clause_profile)
        assert "reconfig" in profile, (
            f"ddmin must keep reconfig occurrence atoms, kept {profile}"
        )
        assert "crash" not in profile, (
            f"no crash clause exists in this plan, yet ddmin kept {profile}"
        )
        assert bug.bundle_path and os.path.exists(bug.bundle_path), (
            f"shrunk witness must leave a ReproBundle, got {bug.bundle_path}"
        )

        # -- anatomy: the skeleton names the reconfig delivery ----------
        assert bug.anatomy and "error" not in bug.anatomy, (
            f"cross-witness anatomy failed: {bug.anatomy}"
        )
        skel = bug.anatomy["skeleton"]
        assert any(label.startswith("deliver:FETCH:") for label in skel), (
            f"the skeleton must name the rejoined replica's FETCH "
            f"delivery (the stale-ISR admission), got {skel[-8:]}"
        )
        t_anatomy = time.perf_counter() - t0

        # -- control: correct spec silent under the same churn ----------
        t1 = time.perf_counter()
        ctrl = reconfig_only_workload(buggy=False)
        st = BatchedSim(ctrl.spec, ctrl.config).run(
            jnp.arange(LANES, dtype=jnp.uint32), max_steps=wl.max_steps
        )
        n_ctrl = int(np.asarray(st.violated).sum())
        assert n_ctrl == 0, (
            f"correct catch-up spec violated on {n_ctrl} lanes under the "
            "same reconfig churn"
        )
        t_control = time.perf_counter() - t1
    finally:
        shutil.rmtree(root, ignore_errors=True)

    print(json.dumps({
        "reconfig_smoke": "ok",
        "violations": n_viol,
        "witnesses": len(bug.witnesses),
        "bug_records": 1,
        "signature": bug.signature[:12],
        "clause_profile": bug.clause_profile,
        "skeleton_len": len(skel),
        "skeleton_sha": bug.anatomy["skeleton_sha"],
        "coverage_bits": rep.coverage_bits,
        "wall_s": {
            "campaign": round(t_campaign, 1),
            "anatomy": round(t_anatomy - t_campaign, 1),
            "control": round(t_control, 1),
            "total": round(time.perf_counter() - t0, 1),
        },
    }))


if __name__ == "__main__":
    main()
