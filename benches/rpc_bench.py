"""Real-mode RPC microbenchmarks — the madsim/benches/rpc.rs analog.

The reference measures (criterion, std mode): empty RPC round-trip latency
(rpc.rs:11-26) and request throughput at payload sizes 16 B..1 MiB
(rpc.rs:28-53) over its real TCP backend. Same harness here, over BOTH real
transports (std/net/mod.rs:33-38 selection analog):

    python benches/rpc_bench.py [--rounds 2000] [--backends tcp,uds,shm]

Prints one JSON line per (backend, measurement).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

PAYLOAD_SIZES = [16, 256, 4 << 10, 64 << 10, 1 << 20]  # rpc.rs:36

from madsim_tpu.net import rpc  # noqa: E402


@rpc.rpc_request
class Echo:
    """Module-level: request types must pickle in production mode."""


async def _bench_backend(backend: str, rounds: int, uds_dir: str) -> list:
    os.environ["MADSIM_NET_BACKEND"] = backend
    if backend in ("uds", "shm"):
        os.environ["MADSIM_UDS_DIR"] = uds_dir

    from madsim_tpu.net import Endpoint

    server = await Endpoint.bind("127.0.0.1:0")

    async def handle(_req, data):
        return None, data  # echo the payload back (rpc.rs echo service)

    rpc.add_rpc_handler_with_data(server, Echo, handle)
    client = await Endpoint.bind("127.0.0.1:0")
    addr = server.local_addr()

    results = []

    # empty round-trip latency (rpc.rs:11-26)
    await rpc.call_with_data(client, addr, Echo(), b"")  # warm
    lat = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        await rpc.call_with_data(client, addr, Echo(), b"")
        lat.append(time.perf_counter() - t0)
    results.append(
        {
            "bench": "rpc_latency_empty",
            "backend": backend,
            "p50_us": round(statistics.median(lat) * 1e6, 1),
            "p99_us": round(sorted(lat)[int(len(lat) * 0.99)] * 1e6, 1),
            "rounds": rounds,
        }
    )

    # payload throughput (rpc.rs:28-53): bytes echoed per second
    for size in PAYLOAD_SIZES:
        payload = os.urandom(size)
        n = max(50, min(rounds, (16 << 20) // size))
        await rpc.call_with_data(client, addr, Echo(), payload)  # warm
        t0 = time.perf_counter()
        for _ in range(n):
            await rpc.call_with_data(client, addr, Echo(), payload)
        wall = time.perf_counter() - t0
        results.append(
            {
                "bench": f"rpc_throughput_{size}B",
                "backend": backend,
                "mb_per_sec": round(size * n * 2 / wall / 1e6, 2),  # both ways
                "calls_per_sec": round(n / wall, 1),
                "rounds": n,
            }
        )

    server.close()
    client.close()
    return results


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--rounds", type=int, default=2000)
    parser.add_argument("--backends", default="tcp,uds,shm")
    args = parser.parse_args()

    with tempfile.TemporaryDirectory(prefix="rpcbench-") as uds_dir:
        for backend in args.backends.split(","):
            # fresh loop per backend: the rpc serve tasks die with the loop
            for row in asyncio.run(
                _bench_backend(backend.strip(), args.rounds, uds_dir)
            ):
                print(json.dumps(row))


if __name__ == "__main__":
    main()
