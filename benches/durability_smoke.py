"""durability-smoke: <80s durability-axis gate for CI.

The r18 DiskFault clause's pitch is that durability is a fault AXIS, not
scenery: a bug class REACHABLE ONLY by destroying unsynced state must
flow through the whole farm — explorer, ddmin, campaign dedup, causal
anatomy — and come out the other side named. This smoke walks that path
on the planted WAL bug (a group-committing server acks appends BEFORE
fsync, `make_wal_spec(buggy_ack_before_fsync=True)`) under a disk-ONLY
plan — no crash clauses, loss pinned low — so the shrunk minimal plan
can only ever blame the durability axis:

  * FIND: one explorer generation over the planted config surfaces the
    bug on multiple fresh seeds (lost acks are seed-dense once disks
    die mid-group-commit);
  * SHRINK: the campaign ddmin-shrinks the first witness and the kept
    minimal plan names `disk` occurrence atoms (crash cannot appear:
    the plan has none to keep);
  * DEDUP: every further violating seed attaches as a witness of ONE
    BugRecord — one bug class, one record, a saved ReproBundle;
  * REPRO: the saved bundle replays bit-identically (repro.replay,
    repeats=2) and still violates at the recorded step/time — the
    bundle carries spec_ref, so `python -m madsim_tpu.repro` works
    from any process;
  * ANATOMY: the r12 cross-witness skeleton names the ack delivery —
    the ACK the server issued for bytes fsync never saw;
  * CONTROL: the fsync-before-ack spec stays silent under the exact
    same dying disks.

Wall times are printed for eyes only. Usage:
python benches/durability_smoke.py  (or `make durability-smoke`)
Exit code != 0 on any assertion failure; prints one JSON line.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LANES = 32
VIRTUAL_SECS = 6.0


def disk_only_workload(buggy: bool = True):
    """The planted WAL config with durability chaos as the ONLY schedule
    clause (loss stays as low message noise). `wal_workload` proper is
    the same shape; this bench pins the knobs so ddmin's verdict is
    unambiguous and the episode cadence outpaces the group-commit."""
    from madsim_tpu.tpu.batch import BatchWorkload
    from madsim_tpu.tpu.spec import SimConfig, pool_kw_for
    from madsim_tpu.tpu.wal import make_wal_spec

    spec = make_wal_spec(4, buggy_ack_before_fsync=buggy)
    cfg = SimConfig(
        horizon_us=int(VIRTUAL_SECS * 1e6),
        **pool_kw_for(
            spec,
            fused=dict(msg_depth_msg=2, msg_spare_slots=2),
            two_handler=dict(msg_depth_msg=2, msg_depth_timer=2),
        ),
        loss_rate=0.02,
        nem_disk_interval_lo_us=300_000,
        nem_disk_interval_hi_us=1_000_000,
        # degraded window shorter than the 120ms group-commit period so
        # crashes regularly land on a dirty, unsynced tail
        nem_disk_slow_lo_us=80_000,
        nem_disk_slow_hi_us=200_000,
        nem_disk_down_lo_us=200_000,
        nem_disk_down_hi_us=600_000,
        nem_disk_torn_rate=0.5,
        nem_disk_extra_us=30_000,
    )
    return BatchWorkload(spec=spec, config=cfg)


def main() -> None:
    t0 = time.perf_counter()
    import jax.numpy as jnp
    import numpy as np

    from madsim_tpu import campaign
    from madsim_tpu.nemesis import FIRE_KINDS
    from madsim_tpu.tpu.engine import BatchedSim

    wl = disk_only_workload(buggy=True)
    sim = BatchedSim(wl.spec, wl.config, triage=True, coverage=True)
    root = tempfile.mkdtemp(prefix="durability_smoke_")
    try:
        # -- find + shrink + dedup: one campaign generation -------------
        camp = campaign.Campaign(
            wl, os.path.join(root, "c"), meta_seed=0, lanes=LANES,
            shrink=True, max_shrinks=2, sim=sim,
            anatomy=True, max_anatomy_witnesses=2,
            # baked into every saved bundle, so `python -m madsim_tpu.repro
            # bundle.json` rebuilds the planted spec from any process
            spec_ref="madsim_tpu.tpu.wal:make_wal_spec",
            spec_kwargs={"n_nodes": 4, "buggy_ack_before_fsync": True},
        )
        rep = camp.run(1)
        t_campaign = time.perf_counter() - t0
        n_viol = len(camp.ex.violations)
        assert n_viol >= 2, (
            f"planted WAL bug found on only {n_viol} candidates — disk "
            "crashes are not landing on unsynced acked appends"
        )

        # -- dedup: one bug class, ONE record ---------------------------
        assert len(camp.bugs) == 1, (
            f"one planted bug must dedup to one BugRecord, got "
            f"{len(camp.bugs)}: "
            f"{[(b.signature[:12], b.violation_kind) for b in camp.bugs]}"
        )
        bug = camp.bugs[0]
        assert bug.shrink_error is None, f"shrink failed: {bug.shrink_error}"
        assert len(bug.witnesses) >= 2, (
            f"seed-dense bug attached only {len(bug.witnesses)} witnesses"
        )

        # -- shrink: the minimal plan blames the durability axis --------
        profile = dict((n, c) for n, c in bug.clause_profile)
        assert "disk" in profile, (
            f"ddmin must keep disk occurrence atoms, kept {profile}"
        )
        assert "crash" not in profile, (
            f"no crash clause exists in this plan, yet ddmin kept {profile}"
        )
        assert bug.bundle_path and os.path.exists(bug.bundle_path), (
            f"shrunk witness must leave a ReproBundle, got {bug.bundle_path}"
        )

        # -- repro: the bundle replays bit-identically ------------------
        from madsim_tpu import repro
        from madsim_tpu.triage import ReproBundle

        rep_replay = repro.replay(
            ReproBundle.load(bug.bundle_path), backend="tpu", repeats=2,
            out=lambda *_: None,
        )
        assert rep_replay.get("violated"), (
            f"repro replay of the shrunk bundle did not violate: {rep_replay}"
        )

        # -- anatomy: the skeleton names the unsynced ack ---------------
        assert bug.anatomy and "error" not in bug.anatomy, (
            f"cross-witness anatomy failed: {bug.anatomy}"
        )
        skel = bug.anatomy["skeleton"]
        assert any(label.startswith("deliver:ACK:") for label in skel), (
            f"the skeleton must name the ACK delivery for bytes fsync "
            f"never saw (the ack-before-fsync mechanism), got {skel[-8:]}"
        )
        t_anatomy = time.perf_counter() - t0

        # -- control: correct spec silent under the same dying disks ----
        t1 = time.perf_counter()
        ctrl = disk_only_workload(buggy=False)
        st = BatchedSim(ctrl.spec, ctrl.config).run(
            jnp.arange(LANES, dtype=jnp.uint32), max_steps=wl.max_steps
        )
        n_ctrl = int(np.asarray(st.violated).sum())
        assert n_ctrl == 0, (
            f"fsync-before-ack spec violated on {n_ctrl} lanes under the "
            "same disk chaos"
        )
        # the control leg still SAW the chaos (dead-clause guard) but
        # never had unsynced durable state to lose
        assert int(np.asarray(st.fires)[
            :, FIRE_KINDS.index("disk_crash")
        ].sum()) > 0
        t_control = time.perf_counter() - t1
    finally:
        shutil.rmtree(root, ignore_errors=True)

    print(json.dumps({
        "durability_smoke": "ok",
        "violations": n_viol,
        "witnesses": len(bug.witnesses),
        "bug_records": 1,
        "signature": bug.signature[:12],
        "clause_profile": bug.clause_profile,
        "skeleton_len": len(skel),
        "skeleton_sha": bug.anatomy["skeleton_sha"],
        "coverage_bits": rep.coverage_bits,
        "wall_s": {
            "campaign": round(t_campaign, 1),
            "anatomy": round(t_anatomy - t_campaign, 1),
            "control": round(t_control, 1),
            "total": round(time.perf_counter() - t0, 1),
        },
    }))


if __name__ == "__main__":
    main()
