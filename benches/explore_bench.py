"""Explorer vs uniform sweep: coverage-per-dispatch and first-bug cost.

The explorer's pitch (docs/explore.md) is that steering lanes toward novel
behavior multiplies bugs-per-execution over the uniform random sweep the
batch path runs today. This bench measures that claim on the SAME two
planted-bug configs benches/ttfb.py sweeps — the deposed-leader re-stamp
under a crash+partition schedule plan, and the chain blind-apply bug under
heavy-tail stragglers — with the same lane budget on both sides:

    uniform:  sequential seeds, `dispatches` chunks of `lanes`, coverage on
    explore:  Explorer(meta_seed=0) — generation 0 IS the uniform sweep's
              first chunk, later generations steer (mutants + swarm)

Reported per config (the acceptance criterion is the dispatch comparison:
the explorer must reach its first violation in no MORE dispatches than the
uniform sweep, and every surfaced violation must carry a ReproBundle):

    coverage_curve          union coverage bits after each dispatch, both
    first_violation_dispatch / wall_to_first_violation_s, both
    coverage_gain_pct       explorer's final union vs uniform's
    violations / bundles    explorer's unique violations + shrunk bundles

Usage: python benches/explore_bench.py [--lanes 256] [--dispatches 8]
Prints one JSON line; bench.py embeds the same rows in BENCH as `explore`.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time


def _repo_root_on_path() -> None:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)


_repo_root_on_path()


def uniform_sweep(
    workload, lanes: int, dispatches: int, first_seed: int = 0,
) -> dict:
    """The baseline: sequential seeds in `dispatches` chunks of `lanes`
    with coverage instrumentation on, from a cold sim (the explorer pays
    its compiles inside its own wall number, so the baseline does too).
    Tracks the union coverage curve and the first violating dispatch."""
    import numpy as np

    from madsim_tpu.explore import popcount_rows
    from madsim_tpu.tpu.engine import BatchedSim, COV_WORDS

    t0 = time.perf_counter()
    sim = BatchedSim(workload.spec, workload.config, coverage=True)
    union = np.zeros((COV_WORDS,), np.uint32)
    curve = []
    first_violation = None
    wall_first = None
    for d in range(dispatches):
        seeds = np.arange(
            first_seed + d * lanes, first_seed + (d + 1) * lanes,
            dtype=np.uint32,
        )
        st = sim.run(seeds, max_steps=workload.max_steps)
        violated = np.asarray(st.violated)
        union |= np.bitwise_or.reduce(
            np.asarray(st.cov.bitmap, np.uint32), axis=0
        )
        curve.append(int(popcount_rows(union)))
        if first_violation is None and violated.any():
            first_violation = d
            wall_first = time.perf_counter() - t0
    return {
        "lanes": lanes,
        "dispatches": dispatches,
        "coverage_curve": curve,
        "coverage_bits": curve[-1] if curve else 0,
        "first_violation_dispatch": first_violation,
        "wall_to_first_violation_s": (
            round(wall_first, 3) if wall_first is not None else None
        ),
        "wall_s": round(time.perf_counter() - t0, 3),
    }


def explore_vs_uniform(
    workload, lanes: int = 256, dispatches: int = 8, meta_seed: int = 0,
    shrink: bool = True, max_shrinks: "int | None" = 8,
    out_dir: "str | None" = None,
) -> dict:
    """One config's comparison row. Both sides run cold with the same
    lane x dispatch budget; the uniform side runs first so its compile
    warms nothing the explorer reuses unfairly (the explorer compiles its
    own triage+coverage program — a strictly BIGGER step)."""
    from madsim_tpu.explore import Explorer

    uni = uniform_sweep(workload, lanes, dispatches)

    if out_dir is None and shrink:
        out_dir = tempfile.mkdtemp(prefix="explore_bundles_")
    t0 = time.perf_counter()
    # the planted bugs are seed-DENSE (every violating lane would cost ~10
    # ddmin dispatches), so the bench caps bundles at `max_shrinks`; the
    # bundle-per-violation capability itself is pinned by tests/test_explore
    ex = Explorer(
        workload, meta_seed=meta_seed, lanes=lanes,
        shrink_violations=shrink, max_shrinks=max_shrinks,
        shrink_kwargs={"out_dir": out_dir} if out_dir else None,
    )
    rep = ex.run(dispatches)
    wall = time.perf_counter() - t0

    bundles = sum(1 for v in rep.violations if v.get("bundle_path"))
    row = {
        "uniform": uni,
        "explore": {
            "lanes": lanes,
            "dispatches": dispatches,
            "meta_seed": meta_seed,
            "coverage_curve": rep.coverage_curve,
            "coverage_bits": rep.coverage_bits,
            "corpus_size": rep.corpus_size,
            "first_violation_dispatch": rep.first_violation_dispatch,
            "violations": len(rep.violations),
            "bundles": bundles,
            "wall_s": round(wall, 3),
        },
    }
    if uni["coverage_bits"]:
        row["coverage_gain_pct"] = round(
            100.0 * (rep.coverage_bits - uni["coverage_bits"])
            / uni["coverage_bits"], 1,
        )
    if (
        uni["first_violation_dispatch"] is not None
        and rep.first_violation_dispatch is not None
    ):
        # positive = explorer needed FEWER dispatches (the acceptance bar
        # is >= 0: generation 0 is the uniform sweep's first chunk, so the
        # explorer can never lose on a first-chunk-dense bug and must win
        # or tie on the rest)
        row["dispatch_advantage"] = (
            uni["first_violation_dispatch"] - rep.first_violation_dispatch
        )
    return row


def explore_all(
    lanes: int = 256, dispatches: int = 8, meta_seed: int = 0,
    shrink: bool = True, max_shrinks: "int | None" = 8,
) -> dict:
    """Both planted-bug configs (shared with benches/ttfb.py)."""
    import ttfb

    rows = {}
    for name, (factory, _host) in ttfb.PLANTED.items():
        try:
            rows[name] = explore_vs_uniform(
                factory(), lanes=lanes, dispatches=dispatches,
                meta_seed=meta_seed, shrink=shrink,
                max_shrinks=max_shrinks,
            )
        except Exception as e:  # noqa: BLE001 - one bad config must not
            # hide the other's number
            rows[name] = {"error": f"{type(e).__name__}: {str(e)[:160]}"}
    return rows


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--lanes", type=int, default=256)
    parser.add_argument("--dispatches", type=int, default=8)
    parser.add_argument("--meta-seed", type=int, default=0)
    parser.add_argument("--no-shrink", action="store_true")
    parser.add_argument("--max-shrinks", type=int, default=8)
    args = parser.parse_args()
    print(
        json.dumps(explore_all(
            args.lanes, args.dispatches, meta_seed=args.meta_seed,
            shrink=not args.no_shrink, max_shrinks=args.max_shrinks,
        )),
        flush=True,
    )


if __name__ == "__main__":
    main()
