"""Explorer vs uniform sweep: coverage-per-dispatch and first-bug cost.

The explorer's pitch (docs/explore.md) is that steering lanes toward novel
behavior multiplies bugs-per-execution over the uniform random sweep the
batch path runs today. This bench measures that claim on the SAME two
planted-bug configs benches/ttfb.py sweeps — the deposed-leader re-stamp
under a crash+partition schedule plan, and the chain blind-apply bug under
heavy-tail stragglers — with the same lane budget on both sides:

    uniform:  sequential seeds, `dispatches` chunks of `lanes`, coverage on
    explore:  Explorer(meta_seed=0) — generation 0 IS the uniform sweep's
              first chunk, later generations steer (mutants + swarm)

Reported per config (the acceptance criterion is the dispatch comparison:
the explorer must reach its first violation in no MORE dispatches than the
uniform sweep, and every surfaced violation must carry a ReproBundle):

    coverage_curve          union coverage bits after each dispatch, both
    first_violation_dispatch / wall_to_first_violation_s, both
    coverage_gain_pct       explorer's final union vs uniform's
    violations / bundles    explorer's unique violations + shrunk bundles

Usage: python benches/explore_bench.py [--lanes 256] [--dispatches 8]
Prints one JSON line; bench.py embeds the same rows in BENCH as `explore`.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time


def _repo_root_on_path() -> None:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)


_repo_root_on_path()


def uniform_sweep(
    workload, lanes: int, dispatches: int, first_seed: int = 0,
) -> dict:
    """The baseline: sequential seeds in `dispatches` chunks of `lanes`
    with coverage instrumentation on, from a cold sim (the explorer pays
    its compiles inside its own wall number, so the baseline does too).
    Tracks the union coverage curve and the first violating dispatch."""
    import numpy as np

    from madsim_tpu.explore import popcount_rows
    from madsim_tpu.tpu.engine import BatchedSim, COV_WORDS

    t0 = time.perf_counter()
    sim = BatchedSim(workload.spec, workload.config, coverage=True)
    union = np.zeros((COV_WORDS,), np.uint32)
    curve = []
    first_violation = None
    wall_first = None
    for d in range(dispatches):
        seeds = np.arange(
            first_seed + d * lanes, first_seed + (d + 1) * lanes,
            dtype=np.uint32,
        )
        st = sim.run(seeds, max_steps=workload.max_steps)
        violated = np.asarray(st.violated)
        union |= np.bitwise_or.reduce(
            np.asarray(st.cov.bitmap, np.uint32), axis=0
        )
        curve.append(int(popcount_rows(union)))
        if first_violation is None and violated.any():
            first_violation = d
            wall_first = time.perf_counter() - t0
    return {
        "lanes": lanes,
        "dispatches": dispatches,
        "coverage_curve": curve,
        "coverage_bits": curve[-1] if curve else 0,
        "first_violation_dispatch": first_violation,
        "wall_to_first_violation_s": (
            round(wall_first, 3) if wall_first is not None else None
        ),
        "wall_s": round(time.perf_counter() - t0, 3),
    }


def explore_vs_uniform(
    workload, lanes: int = 256, dispatches: int = 8, meta_seed: int = 0,
    shrink: bool = True, max_shrinks: "int | None" = 8,
    out_dir: "str | None" = None,
) -> dict:
    """One config's comparison row. Both sides run cold with the same
    lane x dispatch budget; the uniform side runs first so its compile
    warms nothing the explorer reuses unfairly (the explorer compiles its
    own triage+coverage program — a strictly BIGGER step)."""
    from madsim_tpu.explore import Explorer

    uni = uniform_sweep(workload, lanes, dispatches)

    if out_dir is None and shrink:
        out_dir = tempfile.mkdtemp(prefix="explore_bundles_")
    t0 = time.perf_counter()
    # the planted bugs are seed-DENSE (every violating lane would cost ~10
    # ddmin dispatches), so the bench caps bundles at `max_shrinks`; the
    # bundle-per-violation capability itself is pinned by tests/test_explore
    ex = Explorer(
        workload, meta_seed=meta_seed, lanes=lanes,
        shrink_violations=shrink, max_shrinks=max_shrinks,
        shrink_kwargs={"out_dir": out_dir} if out_dir else None,
    )
    rep = ex.run(dispatches)
    wall = time.perf_counter() - t0

    bundles = sum(1 for v in rep.violations if v.get("bundle_path"))
    row = {
        "uniform": uni,
        "explore": {
            "lanes": lanes,
            "dispatches": dispatches,
            "meta_seed": meta_seed,
            "coverage_curve": rep.coverage_curve,
            "coverage_bits": rep.coverage_bits,
            "corpus_size": rep.corpus_size,
            "first_violation_dispatch": rep.first_violation_dispatch,
            "violations": len(rep.violations),
            "bundles": bundles,
            "wall_s": round(wall, 3),
        },
    }
    if uni["coverage_bits"]:
        row["coverage_gain_pct"] = round(
            100.0 * (rep.coverage_bits - uni["coverage_bits"])
            / uni["coverage_bits"], 1,
        )
    if (
        uni["first_violation_dispatch"] is not None
        and rep.first_violation_dispatch is not None
    ):
        # positive = explorer needed FEWER dispatches (the acceptance bar
        # is >= 0: generation 0 is the uniform sweep's first chunk, so the
        # explorer can never lose on a first-chunk-dense bug and must win
        # or tie on the rest)
        row["dispatch_advantage"] = (
            uni["first_violation_dispatch"] - rep.first_violation_dispatch
        )
    return row


def devloop_ab(
    workload, lanes: int = 16, gens: int = 4, window: int = 2,
    meta_seed: int = 0, seen_cap: int = 1 << 12,
) -> dict:
    """Host loop vs device-resident loop (r19, docs/explore.md) on ONE
    shared sim: the same search run both ways, reporting the
    hardware-independent dispatch economics —

      * host syncs (blocking decodes): 1/generation on the host loop
        (`refill_results`) vs 1/WINDOW on the device loop
        (`devloop_results`, `syncs_per_gen <= 1` by construction);
      * device dispatches (init + segments + early-stop reductions,
        `sim.dispatch_count`): the device loop runs whole windows as one
        chain, so its total is strictly below the host loop's;
      * `generations_per_s`, warm (each side runs once cold for compile,
        then once timed) — wall follows the sync count once the tunnel
        RTT dominates, so on CPU this is a sanity number, on TPU the
        claim;

    and `fingerprint_match`: the two faces' reports must be
    bit-identical (the tentpole's acceptance contract)."""
    from madsim_tpu.explore import Explorer
    from madsim_tpu.tpu import engine as eng
    from madsim_tpu.tpu.engine import BatchedSim, make_devloop_plan

    plan = make_devloop_plan(
        workload.config, pop=lanes, top_k=16, seen_cap=seen_cap,
    )
    sim = BatchedSim(
        workload.spec, workload.config, triage=True, coverage=True,
        devloop=plan,
    )

    def run(device: bool) -> dict:
        decodes = [0]
        real_r, real_d = eng.refill_results, eng.devloop_results

        def counted(real):
            def f(st):
                decodes[0] += 1
                return real(st)
            return f

        eng.refill_results = counted(real_r)
        eng.devloop_results = counted(real_d)
        try:
            ex = Explorer(
                workload, meta_seed=meta_seed, lanes=lanes, chunk=lanes,
                shrink_violations=False, seen_cap=seen_cap, sim=sim,
                device_loop=device, device_window=window,
            )
            d0 = sim.dispatch_count
            t0 = time.perf_counter()
            rep = ex.run(gens)
            wall = time.perf_counter() - t0
        finally:
            eng.refill_results, eng.devloop_results = real_r, real_d
        return {
            "dispatches": sim.dispatch_count - d0,
            "syncs": decodes[0],
            "syncs_per_gen": round(decodes[0] / gens, 3),
            "generations_per_s": round(gens / max(wall, 1e-9), 2),
            "wall_s": round(wall, 3),
            "fingerprint": rep.fingerprint(),
        }

    run(False), run(True)  # cold pass: compiles land outside the timing
    host, dev = run(False), run(True)
    fp_match = host.pop("fingerprint") == dev.pop("fingerprint")
    return {
        "lanes": lanes,
        "generations": gens,
        "window": window,
        "host": host,
        "device": dev,
        "fingerprint_match": fp_match,
        "dispatch_ratio": round(
            host["dispatches"] / max(dev["dispatches"], 1), 2
        ),
    }


def explore_all(
    lanes: int = 256, dispatches: int = 8, meta_seed: int = 0,
    shrink: bool = True, max_shrinks: "int | None" = 8,
) -> dict:
    """Both planted-bug configs (shared with benches/ttfb.py)."""
    import ttfb

    rows = {}
    for name, (factory, _host) in ttfb.PLANTED.items():
        try:
            rows[name] = explore_vs_uniform(
                factory(), lanes=lanes, dispatches=dispatches,
                meta_seed=meta_seed, shrink=shrink,
                max_shrinks=max_shrinks,
            )
        except Exception as e:  # noqa: BLE001 - one bad config must not
            # hide the other's number
            rows[name] = {"error": f"{type(e).__name__}: {str(e)[:160]}"}
    return rows


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--lanes", type=int, default=256)
    parser.add_argument("--dispatches", type=int, default=8)
    parser.add_argument("--meta-seed", type=int, default=0)
    parser.add_argument("--no-shrink", action="store_true")
    parser.add_argument("--max-shrinks", type=int, default=8)
    parser.add_argument(
        "--devloop", action="store_true",
        help="run the host-vs-device generation-loop A/B instead "
        "(dispatch counts, syncs/gen, generations/s — docs/explore.md)",
    )
    parser.add_argument("--window", type=int, default=2)
    args = parser.parse_args()
    if args.devloop:
        import ttfb

        factory, _ = ttfb.PLANTED["raft_restamp"]
        print(
            json.dumps(devloop_ab(
                factory(), lanes=args.lanes, gens=args.dispatches,
                window=args.window, meta_seed=args.meta_seed,
            )),
            flush=True,
        )
        return
    print(
        json.dumps(explore_all(
            args.lanes, args.dispatches, meta_seed=args.meta_seed,
            shrink=not args.no_shrink, max_shrinks=args.max_shrinks,
        )),
        flush=True,
    )


if __name__ == "__main__":
    main()
