"""refill-smoke: <60s continuous-batching gate for CI and the tier-1 tier.

The refill engine's whole value proposition is two platform-independent
numbers, so this smoke asserts them without touching wall-clock:

  * LANE OCCUPANCY >= 90% on a synthetic workload mix with a 10x horizon
    spread (one long admission per 8 — the ddmin-probe / short-mutant
    shape): busy-lane-steps / total-lane-steps, counted by the engine's
    own in-carry occupancy counters;
  * the DISPATCH BUDGET: a refill sweep is init + segments + early-stop
    reductions like any chunked sweep — an eager-init-style regression
    (per-retirement host round-trips would be the refill analog of the
    r5 dispatch storm) blows the budget loudly;
  * the LANE-STEP ADVANTAGE >= 2x: total lane-steps the chunked path
    burns for the SAME per-seed results, the hardware-independent form
    of the "ddmin wall-clock down >= 2x" claim (wall follows lane-steps
    once the step is bandwidth-bound — bench.py measures that on-chip);
  * per-seed BIT-IDENTITY of the two paths' violation/step rows on this
    mix (the determinism contract at smoke scale; the full matrix lives
    in tests/test_refill.py).

Wall times are printed for eyes only. Usage:
python benches/refill_smoke.py  (or `make refill-smoke`)
Exit code != 0 on any assertion failure; prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LANES = 16
WAVES = 16  # admissions = LANES * WAVES (deep enough that the tail
#            drain — surviving long lanes after the queue empties —
#            stays amortized, the production serving shape)
SPREAD = 10  # long-to-short horizon ratio
OCCUPANCY_FLOOR = 0.90
ADVANTAGE_FLOOR = 2.0
# init + sweep segments + early-stop reductions for the whole refill
# sweep; the smoke mix finishes in ONE ~2k-iteration segment, so the
# budget is tiny and fixed (see engine.run_state's accounting)
DISPATCH_BUDGET = 6


def main() -> None:
    t0 = time.perf_counter()
    import numpy as np

    import roofline as rl

    row = rl.refill_occupancy(
        lanes=LANES, waves=WAVES, spread=SPREAD, virtual_secs=1.0,
    )
    failures = []
    if row["occupancy_refill"] < OCCUPANCY_FLOOR:
        failures.append(
            f"occupancy {row['occupancy_refill']} < {OCCUPANCY_FLOOR} on "
            f"the {SPREAD}x horizon-spread mix"
        )
    if row["lane_step_advantage"] < ADVANTAGE_FLOOR:
        failures.append(
            f"lane-step advantage {row['lane_step_advantage']} < "
            f"{ADVANTAGE_FLOOR}x vs the chunked path"
        )
    if row["dispatches_refill"] > DISPATCH_BUDGET:
        failures.append(
            f"refill sweep cost {row['dispatches_refill']} dispatches "
            f"(budget {DISPATCH_BUDGET}) — a host round-trip leaked into "
            "the retirement loop?"
        )

    # per-seed bit-identity of the two paths on the same mix (smoke
    # scale): every admission's violation verdict and step counters must
    # match its chunked row exactly
    import dataclasses

    from madsim_tpu.tpu import raft_workload
    from madsim_tpu.tpu.batch import run_batch

    wl = dataclasses.replace(raft_workload(), host_repro=None)
    seeds = range(LANES * 3)
    rc = run_batch(seeds, wl, chunk=LANES, mesh=None, max_traces=0)
    rr = run_batch(seeds, wl, chunk=LANES * 3, mesh=None, max_traces=0,
                   refill=LANES // 2)
    if not np.array_equal(rc.violated, rr.violated):
        failures.append("refill/chunked violation rows differ")
    if not np.array_equal(rc.violation_step, rr.violation_step):
        failures.append("refill/chunked violation_step rows differ")
    if rc.summary["total_events"] != rr.summary["total_events"]:
        failures.append("refill/chunked event totals differ")

    out = {
        "refill_occupancy": row,
        "bit_identity": not any("differ" in f for f in failures),
        "wall_s": round(time.perf_counter() - t0, 1),
        "ok": not failures,
        "failures": failures,
    }
    print(json.dumps(out), flush=True)
    if failures:
        sys.exit(1)


if __name__ == "__main__":
    main()
