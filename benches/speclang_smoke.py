"""speclang-smoke: <60s single-source-spec gate for CI (warm cache).

Speclang's pitch is that ONE spec source is the whole protocol: both
faces are emitted from it, nothing hand-restated, and the emitted spec
is gated by the same prove-don't-trust machinery as the hand modules.
This smoke walks that claim end to end on CPU:

  * DRIFT: the checked-in `speclang/generated/` modules are exactly
    what the current spec sources render to (in-process `emit --check`)
    and every SPECLANG_DIGEST pins its source's sha256 (`make
    speclang-smoke` also runs the CLI form before this script);
  * IDENTITY: the twopc re-derivation's chaotic 16-lane trajectory
    hashes to the SAME pinned golden constant the hand module is held
    to — the compiler added zero operations to the dataflow;
  * BUG: the speclang-native primary-backup protocol's planted
    stale-read bug (apply guard `!=` instead of `>`) violates monotone
    reads on many lanes under its dup/reorder workload, and the
    correct build stays silent under the identical plan;
  * SHRINK+REPLAY: the first violating seed ddmin-shrinks to a
    ReproBundle whose minimal plan keeps the message-clause axis
    (Duplicate/Reorder — crash alone cannot deliver a stale REPL after
    a newer apply), and the bundle replays bit-identically
    (repro.replay, repeats=2) still violating at the recorded step;
  * HOST: the generated host twin reproduces the same bug at a pinned
    seed under a plan-mode Duplicate+Reorder schedule, and the correct
    twin survives the identical plan and seed.

The verifier+certifier leg (`python -m madsim_tpu.analysis --quiet
--rule range --workload backup`) runs as its own Makefile line.

Wall times are printed for eyes only. Usage:
python benches/speclang_smoke.py  (or `make speclang-smoke`)
Exit code != 0 on any assertion failure; prints one JSON line.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LANES = 64
STEPS = 2000


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from madsim_tpu import nemesis, repro, triage
    from madsim_tpu.speclang import device, emit
    from madsim_tpu.speclang.generated import backup_host
    from madsim_tpu.speclang.specs import PROTOCOLS
    from madsim_tpu.speclang.specs import backup as s_backup
    from madsim_tpu.speclang.specs import twopc as s_twopc
    from madsim_tpu.tpu import nemesis as tpu_nemesis
    from madsim_tpu.tpu.engine import BatchedSim
    from madsim_tpu.tpu.spec import SimConfig
    from tests import test_state_layout as tsl

    t0 = time.perf_counter()

    # -- drift: generated modules == a fresh render of their sources ----
    clean, drifted = emit.emit(check=True)
    assert not drifted, (
        f"generated modules drifted from their spec sources: {drifted} — "
        "run `python -m madsim_tpu.speclang emit`"
    )
    assert len(clean) == 2 * len(PROTOCOLS)
    for src in PROTOCOLS:
        want = emit.source_digest(src)
        for face in ("device", "host"):
            mod = __import__(
                f"madsim_tpu.speclang.generated.{src}_{face}",
                fromlist=["SPECLANG_DIGEST"],
            )
            assert mod.SPECLANG_DIGEST == want, (
                f"{src}_{face}.py digest does not pin specs/{src}.py"
            )
    t_drift = time.perf_counter() - t0

    # -- identity: re-derived twopc == the pinned golden trajectory -----
    t1 = time.perf_counter()
    cfg = tpu_nemesis.compile_plan(
        tsl.CHAOS_PLAN, SimConfig(horizon_us=30_000_000)
    )
    st = BatchedSim(device.build(s_twopc.PROTOCOL), cfg).run(
        jnp.arange(16, dtype=jnp.uint32), max_steps=1500,
        dispatch_steps=1500,
    )
    assert tsl.canonical_digest(st) == tsl.GOLDEN["twopc"], (
        "speclang twopc re-derivation diverged from the hand module's "
        "golden digest"
    )
    t_identity = time.perf_counter() - t1

    # -- bug: the planted stale read fires only when planted ------------
    t2 = time.perf_counter()
    wl = device.build_workload(s_backup.PROTOCOL, buggy=True)
    seeds = jnp.arange(LANES, dtype=jnp.uint32)
    stb = BatchedSim(wl.spec, wl.config).run(
        seeds, max_steps=STEPS, dispatch_steps=STEPS
    )
    violated = np.asarray(stb.violated)
    n_bug = int(violated.sum())
    assert n_bug >= 5, (
        f"planted stale-read bug fired on only {n_bug}/{LANES} lanes — "
        "the dup/reorder axis is not delivering stale REPLs"
    )
    wl0 = device.build_workload(s_backup.PROTOCOL)
    st0 = BatchedSim(wl0.spec, wl0.config).run(
        seeds, max_steps=STEPS, dispatch_steps=STEPS
    )
    n_ok = int(np.asarray(st0.violated).sum())
    assert n_ok == 0, (
        f"correct backup spec violated on {n_ok} lanes under its own plan"
    )
    assert int(np.asarray(st0.events).sum()) > 0
    t_bug = time.perf_counter() - t2

    # -- shrink + replay: bundle keeps the message axis and reproduces --
    t3 = time.perf_counter()
    seed = int(np.nonzero(violated)[0][0])
    root = tempfile.mkdtemp(prefix="speclang-smoke-")
    try:
        shrunk = triage.shrink_seed(
            wl, seed, out_dir=root,
            spec_ref="madsim_tpu.speclang.generated.backup_device:make_spec",
            spec_kwargs={"buggy": True},
        )
        bundle = triage.ReproBundle.load(shrunk.bundle_path)
        assert bundle.violation_step > 0
        kept = {
            type(c).__name__
            for c in triage.plan_from_json(bundle.plan).clauses
        }
        assert kept & {"Duplicate", "Reorder"}, (
            f"shrunk plan {sorted(kept)} lost the message-clause axis "
            "the stale-read bug requires"
        )
        rep = repro.replay(
            bundle, backend="tpu", repeats=2, out=lambda *_: None
        )
        assert rep.get("violated"), (
            f"ReproBundle replay of the planted bug did not violate: {rep}"
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)
    t_shrink = time.perf_counter() - t3

    # -- host face: the generated twin reproduces the same bug ----------
    t4 = time.perf_counter()
    plan = nemesis.FaultPlan(
        name="backup-bug",
        clauses=(
            nemesis.Duplicate(rate=0.15),
            nemesis.Reorder(rate=0.3, window_us=250_000),
        ),
    )
    try:
        backup_host.fuzz_one_seed(
            0, virtual_secs=8.0, chaos=False, plan=plan, buggy=True
        )
    except backup_host.InvariantViolation:
        host_hit = True
    else:
        host_hit = False
    assert host_hit, (
        "planted bug did not reproduce on the generated host twin at "
        "the pinned seed"
    )
    r = backup_host.fuzz_one_seed(0, virtual_secs=8.0, chaos=False,
                                  plan=plan)
    assert r["checks"] > 0, "correct host twin never ran its oracle"
    t_host = time.perf_counter() - t4

    print(json.dumps({
        "speclang_smoke": "ok",
        "buggy_lanes": n_bug,
        "shrunk_kept": sorted(kept),
        "secs": {
            "drift": round(t_drift, 2),
            "identity": round(t_identity, 2),
            "bug": round(t_bug, 2),
            "shrink_replay": round(t_shrink, 2),
            "host": round(t_host, 2),
            "total": round(time.perf_counter() - t0, 2),
        },
    }))


if __name__ == "__main__":
    main()
