"""Roofline accounting for the BatchedSim step (VERDICT r4 item 1).

Answers, with measurements rather than assertions:
  1. What is the chip's ATTAINABLE HBM bandwidth (a plain jitted
     read+write streaming kernel, best-of-reps)?
  2. How many bytes does one engine step access (XLA's own cost model on
     the compiled program — counts HBM traffic of every non-fused
     operand/result), and how many bytes is the RESIDENT state pytree?
  3. What fraction of attainable bandwidth does the step achieve, and
     where do the bytes go (ablation attribution: handlers / invariants /
     chaos / pool)?

Usage: python benches/roofline.py [--lanes 32768] [--scan 300]
Prints one JSON line; bench.py embeds the same accounting in BENCH.
"""

from __future__ import annotations

import argparse
import json
import time


def measure_copy_bw_gbs(n_mb: int = 256, reps: int = 3) -> float:
    """Attainable HBM bandwidth by the MARGINAL method: time an on-device
    streaming loop at two loop counts and divide the extra bytes by the
    extra time. Every pitfall here was hit and fixed in round 5:
      * a single-kernel timing over the remote tunnel measures dispatch
        (~100 ms fixed overhead), not bandwidth — hence the loop;
      * `a + 1` loop bodies get algebraically collapsed by XLA into one
        pass — hence the xorshift body;
      * the tunnel relay CACHES identical dispatches — hence a fresh
        seed input per rep;
      * block_until_ready has returned before execution on this stack —
        hence the tiny reduced output that forces a real readback.
    The marginal rate cancels the fixed per-dispatch cost exactly."""
    import jax
    import jax.numpy as jnp

    n = n_mb * (1 << 20) // 4
    L1, L2 = 8, 72

    def make(loops):
        @jax.jit
        def f(seed):
            x = jnp.arange(n, dtype=jnp.uint32) + seed
            y = jax.lax.fori_loop(0, loops, lambda i, a: a ^ (a << 13), x)
            return y[::131072].sum()
        return f

    f1, f2 = make(L1), make(L2)
    int(f1(jnp.uint32(1)))
    int(f2(jnp.uint32(1)))
    rates = []
    for r in range(2, reps + 2):
        t0 = time.perf_counter()
        int(f1(jnp.uint32(r)))
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        int(f2(jnp.uint32(r)))
        t2 = time.perf_counter() - t0
        if t2 > t1:
            rates.append(2 * n * 4 * (L2 - L1) / (t2 - t1) / 1e9)
    if not rates:
        return float("nan")
    # MEDIAN, not max: contention hitting the short-loop rep inflates the
    # marginal rate without bound (one bench run recorded an impossible
    # 2 TB/s); the median of interleaved pairs is robust. Values beyond
    # the v5e's physical 819 GB/s mean every rep was contaminated —
    # clamp and let the consumer see the ceiling rather than fiction.
    med = sorted(rates)[len(rates) // 2]
    return min(med, 819.0)


def compile_sweep_step(sim, state):
    """Compile the program the sweep loop ACTUALLY runs (r8): the
    hot/cold/const split step, with the (hot, cold) carry donated the way
    `_run`'s while_loop aliases it. Accounting bytes for `_step` on the
    flat SimState would charge the loop-invariant ConstState (key0, ctl,
    skew_ppm) as per-step output traffic the real loop no longer pays."""
    import jax

    from madsim_tpu.tpu.engine import split_state

    hot, cold, const = split_state(state)

    def loop_body(h, c, k):
        # drop the TraceRecord exactly like _run's while_loop body does —
        # XLA dead-code-eliminates the record-only work there, so keeping
        # it here would charge bytes the sweep never moves
        h2, c2, _ = sim._step_split(h, c, k)
        return h2, c2

    step = jax.jit(loop_body, donate_argnums=(0, 1))
    return step.lower(hot, cold, const).compile()


def hlo_hbm_bytes(sim, state) -> dict:
    """Model REAL HBM traffic from the optimized HLO: after XLA fusion,
    each top-level instruction of the entry computation reads its operands
    from HBM and writes its result to HBM — fusion-internal values never
    materialize. Summing parameter/result buffer sizes of the remaining
    top-level ops is therefore a faithful (slightly conservative: ignores
    cache reuse between adjacent ops) model of bytes moved, unlike
    cost_analysis()['bytes accessed'], which counts every HLO operand as
    if materialized and overcounts several-fold."""
    import collections
    import re

    compiled = compile_sweep_step(sim, state)
    txt = compiled.as_text()
    # shapes like s32[32768,5,70] / pred[32768,70]{...}; tuples handled by
    # summing their leaf shapes.
    dtype_bytes = {
        "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2,
        "f16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
        "f64": 8,
    }

    def shape_bytes(shape_str: str) -> int:
        total = 0
        for m in re.finditer(r"(\w+)\[([\d,]*)\]", shape_str):
            dt, dims = m.group(1), m.group(2)
            if dt not in dtype_bytes:
                continue
            size = 1
            if dims:
                for d in dims.split(","):
                    size *= int(d)
            total += size * dtype_bytes[dt]
        return total

    # find the entry computation: "ENTRY %name (...) -> ... {"
    entry = []
    in_entry = False
    for line in txt.splitlines():
        if line.startswith("ENTRY "):
            in_entry = True
            continue
        if in_entry:
            if line.startswith("}"):
                break
            entry.append(line.strip())

    traffic = 0
    by_op = collections.Counter()
    n_kernels = 0
    for line in entry:
        # "%name = <shape> <opcode>(operands...)" — result bytes
        m = re.match(r"%?[\w.\-]+ = (\([^)]*\)|[^ ]+) ([\w\-]+)\(", line)
        if not m:
            continue
        shape_str, opcode = m.group(1), m.group(2)
        if opcode in ("parameter", "constant", "get-tuple-element", "tuple",
                      "bitcast"):
            continue
        out_b = shape_bytes(shape_str)
        # operand reads: parse operand shapes when annotated; optimized HLO
        # references operands by name only, so charge reads via a second
        # pass below instead.
        traffic += out_b
        by_op[opcode] += out_b
        n_kernels += 1

    # operand reads: every top-level op reads its operands from HBM. Build
    # name -> bytes for all top-level results + parameters, then charge
    # each op's named operands.
    name_bytes = {}
    for line in entry:
        m = re.match(r"(%?[\w.\-]+) = (\([^)]*\)|[^ ]+) ([\w\-]+)", line)
        if m:
            name_bytes[m.group(1).lstrip("%")] = shape_bytes(m.group(2))
    read_traffic = 0
    for line in entry:
        m = re.match(r"%?[\w.\-]+ = (\([^)]*\)|[^ ]+) ([\w\-]+)\((.*)\)", line)
        if not m:
            continue
        opcode = m.group(2)
        if opcode in ("parameter", "constant", "get-tuple-element", "tuple",
                      "bitcast"):
            continue
        for op in re.finditer(r"%([\w.\-]+)", m.group(3)):
            read_traffic += name_bytes.get(op.group(1), 0)

    mem = compiled.memory_analysis()
    return {
        "hbm_write_bytes": traffic,
        "hbm_read_bytes": read_traffic,
        "hbm_model_bytes": traffic + read_traffic,
        "n_top_level_kernels": n_kernels,
        "top_write_ops": dict(by_op.most_common(8)),
        "temp_bytes": getattr(mem, "temp_size_in_bytes", None),
        "arg_bytes": getattr(mem, "argument_size_in_bytes", None),
        "out_bytes": getattr(mem, "output_size_in_bytes", None),
    }


def state_bytes(state) -> int:
    """Resident bytes of the SimState pytree (the true lower bound on step
    traffic: the carry is read and written every step)."""
    import jax

    return sum(
        x.size * x.dtype.itemsize
        for x in jax.tree_util.tree_leaves(state)
    )


def carry_bytes(state) -> dict:
    """Byte breakdown of the r8 sweep-loop split: hot + cold are the
    while_loop carry (read AND written every step — their 2x is the carry
    floor); const is loop-invariant (read-only, never re-emitted)."""
    import jax

    from madsim_tpu.tpu.engine import split_state

    def nbytes(tree):
        return sum(
            x.size * x.dtype.itemsize
            for x in jax.tree_util.tree_leaves(tree)
        )

    hot, cold, const = split_state(state)
    return {
        "hot_bytes": nbytes(hot),
        "cold_bytes": nbytes(cold),
        "const_bytes": nbytes(const),
    }


# honesty interval around the memory-analysis estimate (see
# mem_bytes_per_step): the residual uncertainty after XLA's own buffer
# assignment is pinned down — multi-read args/temps push true traffic up,
# on-chip reuse pulls it down. ±20% gives a 1.5x-wide bracket, vs the r5
# lo/hi pair's 3.7x (buffer-assignment floor vs per-op HLO sum ceiling).
MEM_EST_INTERVAL = 1.2


def mem_bytes_per_step(sim, state) -> dict:
    """HBM bytes per step from XLA's OWN buffer assignment
    (`compiled.memory_analysis()`): arguments are read once, outputs
    written once, temp buffers written then read — est = arg + out +
    2*temp. This replaces the r5 lo/hi bracket (buffer-assignment lower
    bound vs per-op HLO traffic model upper bound, 3.7x apart) with ONE
    estimate plus a single honesty interval: the remaining uncertainty is
    second-order (a temp read by several kernels counts once here; an
    argument streamed through cache may cost less than its size), far
    smaller than the HLO model's systematic double-counting of every
    fusion boundary. The interval is ±20% (bracket 1.44x <= 1.5x), which
    on the r5 headline config comfortably contains the measured
    achieved-bandwidth point."""
    compiled = compile_sweep_step(sim, state)
    mem = compiled.memory_analysis()
    arg = int(getattr(mem, "argument_size_in_bytes", 0) or 0)
    out = int(getattr(mem, "output_size_in_bytes", 0) or 0)
    tmp = int(getattr(mem, "temp_size_in_bytes", 0) or 0)
    est = arg + out + 2 * tmp
    return {
        "arg_bytes": arg,
        "out_bytes": out,
        "temp_bytes": tmp,
        "bytes_per_step": est,
        "bytes_per_step_lo": int(est / MEM_EST_INTERVAL),
        "bytes_per_step_hi": int(est * MEM_EST_INTERVAL),
    }


def workload_sims(lanes: int, virtual_secs: float = 10.0,
                  client_rate: float = 0.1) -> dict:
    """name -> (BatchedSim, lanes, max_steps) for every device workload,
    at the SAME configs bench.py sweeps (the per-workload roofline must
    describe the step the bench actually runs)."""
    import os
    import sys

    try:
        import bench as benchmod
    except ImportError:  # invoked as `python benches/roofline.py`
        sys.path.insert(
            0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        import bench as benchmod
    from madsim_tpu.tpu import BatchedSim, chain_workload, make_raft_spec
    from madsim_tpu.tpu.kv import kv_workload
    from madsim_tpu.tpu.paxos import paxos_workload
    from madsim_tpu.tpu.twopc import twopc_workload

    raft_spec = make_raft_spec(
        n_nodes=5, client_rate=client_rate, log_capacity=16
    )
    raft_cfg = benchmod.raft_bench_config(virtual_secs)
    kv = kv_workload(virtual_secs=virtual_secs)
    tp = twopc_workload(virtual_secs=virtual_secs)
    px = paxos_workload(virtual_secs=virtual_secs)
    ch = chain_workload(virtual_secs=virtual_secs)
    return {
        "raft": (BatchedSim(raft_spec, raft_cfg), lanes,
                 int(virtual_secs * 600) + 2000),
        "kv": (BatchedSim(kv.spec, kv.config), lanes,
               int(virtual_secs * 1200) + 2000),
        "twopc": (BatchedSim(tp.spec, tp.config), lanes,
                  int(virtual_secs * 1600) + 2000),
        "paxos": (BatchedSim(px.spec, px.config), lanes,
                  int(virtual_secs * 1600) + 2000),
        "chain": (BatchedSim(ch.spec, ch.config), lanes,
                  int(virtual_secs * 2400) + 2000),
    }


def workload_roofline_row(sim, lanes: int, bw_gbs: float, scan: int = 300,
                          warm_steps: int = 200, timed: bool = True) -> dict:
    """One per-workload roofline row: resident state bytes, the
    memory-analysis bytes/step estimate (+ honesty interval), and — when
    `timed` — the measured step time with achieved bandwidth and the
    carry floor (state read+write at attainable bandwidth: the step's
    hard lower bound; step_over_floor says how far above it the step
    runs, i.e. how much headroom intermediates still cost)."""
    import jax
    import jax.numpy as jnp

    state = sim.run_steps(sim.init(jnp.arange(lanes)), warm_steps)
    jax.block_until_ready(state)
    mem = mem_bytes_per_step(sim, state)
    sbytes = state_bytes(state)
    cb = carry_bytes(state)
    # the carry floor in BYTES: the while_loop carry (hot + cold) is read
    # and written every step; the loop-invariant const tree is read-only
    # and excluded (r8 — that exclusion is the point of the split)
    floor_bytes = 2 * (cb["hot_bytes"] + cb["cold_bytes"])
    floor_ms = floor_bytes / (bw_gbs * 1e9) * 1e3
    row = {
        "lanes": lanes,
        "state_bytes": sbytes,
        "state_bytes_per_lane": round(sbytes / lanes, 1),
        **cb,
        "bytes_per_step": mem["bytes_per_step"],
        "bytes_per_step_lo": mem["bytes_per_step_lo"],
        "bytes_per_step_hi": mem["bytes_per_step_hi"],
        "carry_floor_bytes": floor_bytes,
        # the layout-budget headline (asserted by bench_smoke): how many
        # times the carry's unavoidable read+write the step's estimated
        # traffic is — 1.0 would mean zero intermediate HBM traffic
        "est_over_floor": round(mem["bytes_per_step"] / floor_bytes, 2),
        "carry_floor_ms": round(floor_ms, 3),
    }
    if timed:
        ms = time_step_ms(sim, state, scan, lanes=lanes)
        row.update({
            "step_ms": round(ms, 3),
            "achieved_gbs": round(
                mem["bytes_per_step"] / (ms / 1e3) / 1e9, 1
            ),
            "pct_of_attainable": round(
                mem["bytes_per_step"] / (ms / 1e3) / 1e9 / bw_gbs * 100, 1
            ),
            # the conservative utilization claim (ISSUE 6 bar): achieved
            # bandwidth computed from the LO-bound bytes estimate
            "pct_of_attainable_lo": round(
                mem["bytes_per_step_lo"] / (ms / 1e3) / 1e9 / bw_gbs * 100,
                1,
            ),
            "step_over_floor": round(ms / floor_ms, 2),
        })
    return row


def per_workload_roofline(lanes: int = 32768, scan: int = 300,
                          timed: bool = True) -> dict:
    """The per-workload roofline table (r6): one row per device workload,
    so 'bandwidth-bound' is a per-workload number and a trailing workload
    shows WHERE it trails (state bytes? bytes/step? utilization?)."""
    bw = measure_copy_bw_gbs()
    rows = {}
    for name, (sim, wl_lanes, _steps) in workload_sims(lanes).items():
        rows[name] = workload_roofline_row(
            sim, wl_lanes, bw, scan=scan, timed=timed
        )
    return {"attainable_hbm_gbs": round(bw, 1), "rows": rows}


def _spread_mix_sim(virtual_secs: float):
    """The 10x-horizon-spread workload mix's sim (shared by
    refill_occupancy and mesh_scaling): raft under a crash+loss plan."""
    from madsim_tpu import nemesis as nem
    from madsim_tpu.tpu import make_raft_spec
    from madsim_tpu.tpu import nemesis as tn
    from madsim_tpu.tpu.engine import BatchedSim
    from madsim_tpu.tpu.spec import SimConfig

    horizon = int(virtual_secs * 1e6)
    plan = nem.FaultPlan(name="refill-occ", clauses=(
        nem.Crash(interval_lo_us=horizon // 6, interval_hi_us=horizon // 2,
                  down_lo_us=horizon // 8, down_hi_us=horizon // 3),
        nem.MsgLoss(rate=0.05),
    ))
    cfg = tn.compile_plan(plan, SimConfig(horizon_us=horizon))
    return BatchedSim(make_raft_spec(), cfg, triage=True), horizon


def _spread_ctl_rows(h):
    """Per-admission TriageCtl rows for a horizon column `h` (int64 us)."""
    import numpy as np

    import jax.numpy as jnp
    from madsim_tpu.tpu.engine import TriageCtl
    from madsim_tpu.tpu.spec import REBASE_US

    n = len(h)
    return TriageCtl(
        off=jnp.zeros((n,), jnp.int32),
        occ=jnp.zeros((n, 4), jnp.int32),
        rate_scale=jnp.ones((n, 3), jnp.float32),
        h_epoch=jnp.asarray((h // REBASE_US).astype(np.int32)),
        h_off=jnp.asarray((h % REBASE_US).astype(np.int32)),
    )


def mesh_scaling(
    lanes: int = 16, waves: int = 16, spread: int = 10,
    long_every: int = 8, virtual_secs: float = 1.0,
    device_counts=(1, 2, 4, 8), max_steps: int = 50_000,
) -> dict:
    """The multi-chip fleet's headline table (r10, docs/multichip.md):
    the sharded refill sweep on the 10x horizon-spread mix at 1/2/4/8
    devices with EQUAL per-device lanes and equal per-device queue depth
    (admissions scale with the device count). Per row: seeds/s (wall —
    hardware-dependent), per-device occupancy, and the aggregate
    LANE-STEP THROUGHPUT per sweep iteration (busy-lane-steps / max
    device iters — the hardware-independent scaling number: one device
    caps at `lanes` per iteration, D devices at D * lanes).
    `scaling_vs_1dev` on the D-device row is that number over the
    1-device row's; the multichip smoke asserts >= 6x at D = 8.
    Device counts beyond the visible device count are skipped."""
    import numpy as np

    import jax
    from madsim_tpu.tpu.engine import (
        refill_results, refill_results_sharded,
    )

    sim, horizon = _spread_mix_sim(virtual_secs)
    devs = jax.devices()
    rows = []
    base_tp = None
    for D in device_counts:
        if D > len(devs):
            continue
        A = lanes * waves * D
        seeds = np.arange(A, dtype=np.uint32)
        h = np.where(
            np.arange(A) % long_every == 0, horizon, horizon // spread
        ).astype(np.int64)
        ctl = _spread_ctl_rows(h)
        t0 = time.perf_counter()
        if D == 1:
            st = sim.run_refill(
                seeds, lanes=lanes, max_steps=max_steps, ctl=ctl
            )
            res = refill_results(st)
            per_dev = [{
                "iters": res["iters"],
                "busy_lane_steps": res["busy_lane_steps"],
                "total_lane_steps": res["total_lane_steps"],
                "occupancy": res["occupancy"],
            }]
            tp = res["busy_lane_steps"] / max(res["iters"], 1)
        else:
            mesh = jax.sharding.Mesh(np.array(devs[:D]), ("seeds",))
            st = sim.run_refill_sharded(
                seeds, lanes=lanes, mesh=mesh, max_steps=max_steps,
                ctl=ctl,
            )
            res = refill_results_sharded(st, admissions=A)
            per_dev = res["per_device"]
            tp = res["lane_steps_per_iter"]
        wall_s = time.perf_counter() - t0
        if base_tp is None:
            base_tp = tp
        rows.append({
            "devices": D,
            "admissions": A,
            "lanes_per_device": lanes,
            "seeds_per_sec": round(A / max(wall_s, 1e-9), 1),
            "wall_ms": round(wall_s * 1e3, 1),
            "occupancy": round(float(res["occupancy"]), 4),
            "per_device_occupancy": [
                round(float(p["occupancy"]), 4) for p in per_dev
            ],
            "lane_steps_per_iter": round(tp, 2),
            "scaling_vs_1dev": round(tp / max(base_tp, 1e-9), 2),
        })
    return {
        "horizon_spread": spread,
        "long_every": long_every,
        "visible_devices": len(devs),
        "rows": rows,
    }


def refill_occupancy(
    lanes: int = 256, waves: int = 8, spread: int = 10,
    long_every: int = 8, virtual_secs: float = 2.0,
    max_steps: int = 50_000,
) -> dict:
    """The continuous-batching headline metric (r9): LANE OCCUPANCY —
    busy-lane-steps / total-lane-steps per dispatch — on a synthetic
    workload mix with a `spread`x horizon spread (one long admission per
    `long_every`, the ddmin-probe / short-mutant shape), refill vs the
    chunked path on the SAME admissions. Also reports the lane-step
    advantage: how many total lane-steps the chunked path burns per
    refill lane-step for identical per-seed results (wall-clock-free, so
    the number is hardware-independent; the wall ratio follows it once
    the step is bandwidth-bound). Reported into BENCH by bench.py and
    asserted >= 0.9 occupancy by `make refill-smoke`."""
    import numpy as np

    from madsim_tpu.tpu.engine import refill_results

    sim, horizon = _spread_mix_sim(virtual_secs)
    A = lanes * waves
    seeds = np.arange(A, dtype=np.uint32)
    h = np.where(
        np.arange(A) % long_every == 0, horizon, horizon // spread
    ).astype(np.int64)

    def ctl_rows(sel):
        return _spread_ctl_rows(h[sel])

    all_rows = ctl_rows(np.ones((A,), bool))
    t0 = time.perf_counter()
    d0 = sim.dispatch_count
    st = sim.run_refill(seeds, lanes=lanes, max_steps=max_steps,
                        ctl=all_rows)
    res = refill_results(st)
    refill_ms = (time.perf_counter() - t0) * 1e3
    refill_disp = sim.dispatch_count - d0

    chunk_busy = chunk_total = 0
    t0 = time.perf_counter()
    d0 = sim.dispatch_count
    for off in range(0, A, lanes):
        sel = np.zeros((A,), bool)
        sel[off:off + lanes] = True
        stc = sim.run(seeds[off:off + lanes], max_steps=max_steps,
                      dispatch_steps=max_steps, ctl=ctl_rows(sel))
        steps = np.asarray(stc.steps, np.int64)
        chunk_busy += int(steps.sum())
        chunk_total += int(steps.max(initial=0)) * steps.shape[0]
    chunked_ms = (time.perf_counter() - t0) * 1e3
    chunked_disp = sim.dispatch_count - d0

    return {
        "lanes": lanes,
        "admissions": A,
        "horizon_spread": spread,
        "long_every": long_every,
        "occupancy_refill": round(float(res["occupancy"]), 4),
        "occupancy_chunked": round(chunk_busy / max(chunk_total, 1), 4),
        "busy_lane_steps": res["busy_lane_steps"],
        "total_lane_steps_refill": res["total_lane_steps"],
        "total_lane_steps_chunked": chunk_total,
        # chunked lane-steps burned per refill lane-step, same results
        "lane_step_advantage": round(
            chunk_total / max(res["total_lane_steps"], 1), 2
        ),
        "dispatches_refill": refill_disp,
        "dispatches_chunked": chunked_disp,
        "refill_wall_ms": round(refill_ms, 1),
        "chunked_wall_ms": round(chunked_ms, 1),
    }


def step_cost(sim, state):
    """XLA cost analysis of the compiled single-step program."""
    compiled = compile_sweep_step(sim, state)
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    return {
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        "flops": float(ca.get("flops", 0.0)),
    }


def time_step_ms(sim, state, scan: int, reps: int = 3, lanes: int = 0) -> float:
    """Median per-step ms over `reps` fresh-seed scan chunks (the bench
    methodology: fresh seeds defeat the tunnel relay's dispatch cache)."""
    import jax
    import jax.numpy as jnp

    jax.block_until_ready(sim.run_steps(state, scan))
    walls = []
    for r in range(1, reps + 1):
        st = sim.run_steps(
            sim.init(jnp.arange(r * lanes, (r + 1) * lanes)), 200
        )
        jax.block_until_ready(st)
        t0 = time.perf_counter()
        jax.block_until_ready(sim.run_steps(st, scan))
        walls.append((time.perf_counter() - t0) / scan * 1e3)
    return sorted(walls)[len(walls) // 2]


def roofline(lanes: int = 32768, scan: int = 300, variants: bool = True) -> dict:
    import dataclasses

    import jax.numpy as jnp

    import bench as benchmod
    from madsim_tpu.tpu import BatchedSim, make_raft_spec
    from madsim_tpu.tpu.spec import Outbox

    spec = make_raft_spec(n_nodes=5, client_rate=0.1)
    cfg = benchmod.raft_bench_config(10.0)
    sim = BatchedSim(spec, cfg)
    state = sim.run_steps(sim.init(jnp.arange(lanes)), 200)

    bw = measure_copy_bw_gbs()
    cost = step_cost(sim, state)
    sbytes = state_bytes(state)
    cb = carry_bytes(state)
    hlo = hlo_hbm_bytes(sim, state)
    mem = mem_bytes_per_step(sim, state)
    ms = time_step_ms(sim, state, scan, lanes=lanes)
    floor_bytes = 2 * (cb["hot_bytes"] + cb["cold_bytes"])

    out = {
        "attainable_hbm_gbs": round(bw, 1),
        "step_ms": round(ms, 3),
        "step_bytes_accessed": cost["bytes_accessed"],
        "step_flops": cost["flops"],
        "state_bytes": sbytes,
        **cb,
        "carry_floor_bytes": floor_bytes,
        "est_over_floor": round(mem["bytes_per_step"] / floor_bytes, 2),
        # the headline estimate: XLA buffer assignment (arg + out +
        # 2*temp) with its +-20% honesty interval; the HLO per-op model
        # below is kept as a diagnostic (it systematically double-counts
        # fusion boundaries — see mem_bytes_per_step)
        "bytes_per_step": mem["bytes_per_step"],
        "bytes_per_step_lo": mem["bytes_per_step_lo"],
        "bytes_per_step_hi": mem["bytes_per_step_hi"],
        "hlo_model": hlo,
        "achieved_gbs": round(
            mem["bytes_per_step"] / (ms / 1e3) / 1e9, 1
        ),
        "pct_of_attainable": round(
            mem["bytes_per_step"] / (ms / 1e3) / 1e9 / bw * 100, 1
        ),
        "pct_of_attainable_lo": round(
            mem["bytes_per_step_lo"] / (ms / 1e3) / 1e9 / bw * 100, 1
        ),
        "arith_intensity_flops_per_byte": round(
            cost["flops"] / max(mem["bytes_per_step"], 1), 3
        ),
    }

    if variants:
        # ablation attribution, bytes AND ms per ablated phase
        def id_on_message(s, nid, src, kind, payload, now, key):
            E = spec.max_out_msg
            return (
                s,
                Outbox(
                    valid=jnp.zeros((E,), jnp.bool_),
                    dst=jnp.zeros((E,), jnp.int32),
                    kind=jnp.zeros((E,), jnp.int32),
                    payload=jnp.zeros((E, spec.payload_width), jnp.int32),
                ),
                jnp.int32(-1),
            )

        def id_on_timer(s, nid, now, key):
            E = spec.max_out
            return (
                s,
                Outbox(
                    valid=jnp.zeros((E,), jnp.bool_),
                    dst=jnp.zeros((E,), jnp.int32),
                    kind=jnp.zeros((E,), jnp.int32),
                    payload=jnp.zeros((E, spec.payload_width), jnp.int32),
                ),
                now + 50_000,
            )

        def id_on_event(s, nid, src, kind, payload, now, key):
            E = spec.max_out
            return (
                s,
                Outbox(
                    valid=jnp.zeros((E,), jnp.bool_),
                    dst=jnp.zeros((E,), jnp.int32),
                    kind=jnp.zeros((E,), jnp.int32),
                    payload=jnp.zeros((E, spec.payload_width), jnp.int32),
                ),
                jnp.where(kind == -1, now + 50_000, jnp.int32(-1)),
            )

        ablations = {
            "no_handlers": dataclasses.replace(
                spec, on_message=id_on_message, on_timer=id_on_timer,
                on_event=id_on_event,
            ),
            "no_invariants": dataclasses.replace(
                spec,
                check_invariants=lambda ns, alive, now: jnp.bool_(True),
            ),
        }
        for name, aspec in ablations.items():
            asim = BatchedSim(aspec, cfg)
            astate = asim.run_steps(asim.init(jnp.arange(lanes)), 200)
            acost = step_cost(asim, astate)
            ams = time_step_ms(asim, astate, scan, lanes=lanes)
            out[name] = {
                "step_ms": round(ams, 3),
                "bytes_accessed": acost["bytes_accessed"],
                "attrib_ms": round(out["step_ms"] - ams, 3),
                "attrib_bytes": cost["bytes_accessed"] - acost["bytes_accessed"],
            }
    return out


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--lanes", type=int, default=32768)
    parser.add_argument("--scan", type=int, default=300)
    parser.add_argument("--no-variants", action="store_true")
    parser.add_argument(
        "--per-workload", action="store_true",
        help="emit one roofline row per device workload instead of the "
        "headline-raft deep dive",
    )
    parser.add_argument(
        "--occupancy", action="store_true",
        help="emit the continuous-batching lane-occupancy row (refill vs "
        "chunked on a 10x horizon-spread mix) instead of the deep dive",
    )
    args = parser.parse_args()
    if args.occupancy:
        print(json.dumps(refill_occupancy()), flush=True)
        return
    if args.per_workload:
        print(json.dumps(per_workload_roofline(args.lanes, args.scan)),
              flush=True)
        return
    print(
        json.dumps(
            roofline(args.lanes, args.scan, variants=not args.no_variants)
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
