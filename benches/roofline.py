"""Roofline accounting for the BatchedSim step (VERDICT r4 item 1).

Answers, with measurements rather than assertions:
  1. What is the chip's ATTAINABLE HBM bandwidth (a plain jitted
     read+write streaming kernel, best-of-reps)?
  2. How many bytes does one engine step access (XLA's own cost model on
     the compiled program — counts HBM traffic of every non-fused
     operand/result), and how many bytes is the RESIDENT state pytree?
  3. What fraction of attainable bandwidth does the step achieve, and
     where do the bytes go (ablation attribution: handlers / invariants /
     chaos / pool)?

Usage: python benches/roofline.py [--lanes 32768] [--scan 300]
Prints one JSON line; bench.py embeds the same accounting in BENCH.
"""

from __future__ import annotations

import argparse
import json
import time


def measure_copy_bw_gbs(n_mb: int = 256, reps: int = 3) -> float:
    """Attainable HBM bandwidth by the MARGINAL method: time an on-device
    streaming loop at two loop counts and divide the extra bytes by the
    extra time. Every pitfall here was hit and fixed in round 5:
      * a single-kernel timing over the remote tunnel measures dispatch
        (~100 ms fixed overhead), not bandwidth — hence the loop;
      * `a + 1` loop bodies get algebraically collapsed by XLA into one
        pass — hence the xorshift body;
      * the tunnel relay CACHES identical dispatches — hence a fresh
        seed input per rep;
      * block_until_ready has returned before execution on this stack —
        hence the tiny reduced output that forces a real readback.
    The marginal rate cancels the fixed per-dispatch cost exactly."""
    import jax
    import jax.numpy as jnp

    n = n_mb * (1 << 20) // 4
    L1, L2 = 8, 72

    def make(loops):
        @jax.jit
        def f(seed):
            x = jnp.arange(n, dtype=jnp.uint32) + seed
            y = jax.lax.fori_loop(0, loops, lambda i, a: a ^ (a << 13), x)
            return y[::131072].sum()
        return f

    f1, f2 = make(L1), make(L2)
    int(f1(jnp.uint32(1)))
    int(f2(jnp.uint32(1)))
    rates = []
    for r in range(2, reps + 2):
        t0 = time.perf_counter()
        int(f1(jnp.uint32(r)))
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        int(f2(jnp.uint32(r)))
        t2 = time.perf_counter() - t0
        if t2 > t1:
            rates.append(2 * n * 4 * (L2 - L1) / (t2 - t1) / 1e9)
    if not rates:
        return float("nan")
    # MEDIAN, not max: contention hitting the short-loop rep inflates the
    # marginal rate without bound (one bench run recorded an impossible
    # 2 TB/s); the median of interleaved pairs is robust. Values beyond
    # the v5e's physical 819 GB/s mean every rep was contaminated —
    # clamp and let the consumer see the ceiling rather than fiction.
    med = sorted(rates)[len(rates) // 2]
    return min(med, 819.0)


def compile_sweep_step(sim, state):
    """Compile the program the sweep loop ACTUALLY runs (r8): the
    hot/cold/const split step, with the (hot, cold) carry donated the way
    `_run`'s while_loop aliases it. Accounting bytes for `_step` on the
    flat SimState would charge the loop-invariant ConstState (key0, ctl,
    skew_ppm) as per-step output traffic the real loop no longer pays.

    Memoized per (sim, state shapes): hlo_hbm_bytes, kernel_rows and
    mem_bytes_per_step all walk the SAME compiled program, and on a real
    chip this compile is the dominant roofline cost — it must be paid
    once per (workload, lane count), not once per accounting view."""
    import jax

    from madsim_tpu.tpu.engine import split_state

    key = tuple(
        (leaf.shape, str(leaf.dtype))
        for leaf in jax.tree_util.tree_leaves(state)
    )
    cache = sim.__dict__.setdefault("_sweep_step_compiled", {})
    if key in cache:
        return cache[key]
    hot, cold, const = split_state(state)

    def loop_body(h, c, k):
        # drop the TraceRecord exactly like _run's while_loop body does —
        # XLA dead-code-eliminates the record-only work there, so keeping
        # it here would charge bytes the sweep never moves
        h2, c2, _ = sim._step_split(h, c, k)
        return h2, c2

    step = jax.jit(loop_body, donate_argnums=(0, 1))
    cache[key] = step.lower(hot, cold, const).compile()
    return cache[key]


# shapes like s32[32768,5,70] / pred[32768,70]{...}; tuples handled by
# summing their leaf shapes
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2,
    "f16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
    "f64": 8,
}
# HLO opcodes that are bookkeeping, not kernels (no HBM traffic of their
# own after buffer assignment)
_NON_KERNEL_OPS = (
    "parameter", "constant", "get-tuple-element", "tuple", "bitcast",
)


def _shape_bytes(shape_str: str) -> int:
    import re

    total = 0
    for m in re.finditer(r"(\w+)\[([\d,]*)\]", shape_str):
        dt, dims = m.group(1), m.group(2)
        if dt not in _DTYPE_BYTES:
            continue
        size = 1
        if dims:
            for d in dims.split(","):
                size *= int(d)
        total += size * _DTYPE_BYTES[dt]
    return total


def _entry_lines(txt: str) -> list:
    """The entry computation's instruction lines ("ENTRY %name ... {" to
    its closing brace), stripped."""
    entry = []
    in_entry = False
    for line in txt.splitlines():
        if line.startswith("ENTRY "):
            in_entry = True
            continue
        if in_entry:
            if line.startswith("}"):
                break
            entry.append(line.strip())
    return entry


def _entry_kernels(txt: str) -> list:
    """(name, opcode, out_bytes, read_bytes) per top-level kernel of the
    entry computation — the shared parse behind `hlo_hbm_bytes` and
    `kernel_rows`. After XLA fusion each remaining top-level instruction
    is one launched kernel: it reads its named operands from HBM and
    writes its result; fusion-internal values never materialize."""
    import re

    entry = _entry_lines(txt)
    # name -> bytes for all top-level results + parameters (operand reads
    # are charged by name: optimized HLO references operands by name only)
    name_bytes = {}
    for line in entry:
        m = re.match(r"(%?[\w.\-]+) = (\([^)]*\)|[^ ]+) ([\w\-]+)", line)
        if m:
            name_bytes[m.group(1).lstrip("%")] = _shape_bytes(m.group(2))
    kernels = []
    for line in entry:
        m = re.match(
            r"(%?[\w.\-]+) = (\([^)]*\)|[^ ]+) ([\w\-]+)\((.*)\)", line
        )
        if not m:
            continue
        name, shape_str, opcode, operands = m.groups()
        if opcode in _NON_KERNEL_OPS:
            continue
        read_b = sum(
            name_bytes.get(op.group(1), 0)
            for op in re.finditer(r"%([\w.\-]+)", operands)
        )
        kernels.append(
            (name.lstrip("%"), opcode, _shape_bytes(shape_str), read_b)
        )
    return kernels


def hlo_hbm_bytes(sim, state) -> dict:
    """Model REAL HBM traffic from the optimized HLO: after XLA fusion,
    each top-level instruction of the entry computation reads its operands
    from HBM and writes its result to HBM — fusion-internal values never
    materialize. Summing parameter/result buffer sizes of the remaining
    top-level ops is therefore a faithful (slightly conservative: ignores
    cache reuse between adjacent ops) model of bytes moved, unlike
    cost_analysis()['bytes accessed'], which counts every HLO operand as
    if materialized and overcounts several-fold."""
    import collections

    compiled = compile_sweep_step(sim, state)
    kernels = _entry_kernels(compiled.as_text())
    by_op = collections.Counter()
    for _name, opcode, out_b, _read_b in kernels:
        by_op[opcode] += out_b
    traffic = sum(k[2] for k in kernels)
    read_traffic = sum(k[3] for k in kernels)

    mem = compiled.memory_analysis()
    return {
        "hbm_write_bytes": traffic,
        "hbm_read_bytes": read_traffic,
        "hbm_model_bytes": traffic + read_traffic,
        "n_top_level_kernels": len(kernels),
        "top_write_ops": dict(by_op.most_common(8)),
        "temp_bytes": getattr(mem, "temp_size_in_bytes", None),
        "arg_bytes": getattr(mem, "argument_size_in_bytes", None),
        "out_bytes": getattr(mem, "output_size_in_bytes", None),
    }


def kernel_rows(sim, state, top: int = 12) -> list:
    """PER-FUSED-KERNEL HBM attribution (r13; the BENCH `kernel_rows`
    key): the sweep-step program's top-level kernels ranked by modeled
    HBM bytes (result written + operands read — the `hlo_hbm_bytes`
    traffic model, per kernel), each with its estimated share of step
    TIME. The step is bandwidth-bound (docs/perf_notes.md), so a
    kernel's byte share IS its time share to first order — this is the
    steering table a perf round (or the autotuner's future knob
    proposals) reads to know which fusion to attack next. Kernels below
    the top `top` fold into one "(other)" row so the table stays
    readable; shares always sum to ~100."""
    compiled = compile_sweep_step(sim, state)
    kernels = _entry_kernels(compiled.as_text())
    total = sum(out_b + read_b for _n, _o, out_b, read_b in kernels) or 1
    ranked = sorted(
        kernels, key=lambda k: k[2] + k[3], reverse=True
    )
    rows = []
    for name, opcode, out_b, read_b in ranked[: max(0, int(top))]:
        rows.append({
            "kernel": name,
            "op": opcode,
            "write_bytes": out_b,
            "read_bytes": read_b,
            "bytes": out_b + read_b,
            "time_share_pct": round((out_b + read_b) / total * 100, 2),
        })
    rest = ranked[max(0, int(top)):]
    if rest:
        out_b = sum(k[2] for k in rest)
        read_b = sum(k[3] for k in rest)
        rows.append({
            "kernel": f"(other x{len(rest)})",
            "op": "(other)",
            "write_bytes": out_b,
            "read_bytes": read_b,
            "bytes": out_b + read_b,
            "time_share_pct": round((out_b + read_b) / total * 100, 2),
        })
    return rows


def workload_kernel_rows(sim, lanes: int, top: int = 12) -> list:
    """`kernel_rows` for a workload at a lane count. The attribution is
    a walk of the COMPILED step's HLO text, which depends on state
    shapes only — never on values — so a fresh init suffices (no settle
    steps), and the compile itself is shared with the roofline rows via
    the compile_sweep_step memo."""
    import jax.numpy as jnp

    return kernel_rows(sim, sim.init(jnp.arange(lanes)), top=top)


def state_bytes(state) -> int:
    """Resident bytes of the SimState pytree (the true lower bound on step
    traffic: the carry is read and written every step)."""
    import jax

    return sum(
        x.size * x.dtype.itemsize
        for x in jax.tree_util.tree_leaves(state)
    )


def carry_bytes(state) -> dict:
    """Byte breakdown of the r8 sweep-loop split: hot + cold are the
    while_loop carry (read AND written every step — their 2x is the carry
    floor); const is loop-invariant (read-only, never re-emitted)."""
    import jax

    from madsim_tpu.tpu.engine import split_state

    def nbytes(tree):
        return sum(
            x.size * x.dtype.itemsize
            for x in jax.tree_util.tree_leaves(tree)
        )

    hot, cold, const = split_state(state)
    return {
        "hot_bytes": nbytes(hot),
        "cold_bytes": nbytes(cold),
        "const_bytes": nbytes(const),
    }


# honesty interval around the memory-analysis estimate (see
# mem_bytes_per_step): the residual uncertainty after XLA's own buffer
# assignment is pinned down — multi-read args/temps push true traffic up,
# on-chip reuse pulls it down. ±20% gives a 1.5x-wide bracket, vs the r5
# lo/hi pair's 3.7x (buffer-assignment floor vs per-op HLO sum ceiling).
MEM_EST_INTERVAL = 1.2


def mem_bytes_per_step(sim, state) -> dict:
    """HBM bytes per step from XLA's OWN buffer assignment
    (`compiled.memory_analysis()`): arguments are read once, outputs
    written once, temp buffers written then read — est = arg + out +
    2*temp. This replaces the r5 lo/hi bracket (buffer-assignment lower
    bound vs per-op HLO traffic model upper bound, 3.7x apart) with ONE
    estimate plus a single honesty interval: the remaining uncertainty is
    second-order (a temp read by several kernels counts once here; an
    argument streamed through cache may cost less than its size), far
    smaller than the HLO model's systematic double-counting of every
    fusion boundary. The interval is ±20% (bracket 1.44x <= 1.5x), which
    on the r5 headline config comfortably contains the measured
    achieved-bandwidth point."""
    compiled = compile_sweep_step(sim, state)
    mem = compiled.memory_analysis()
    arg = int(getattr(mem, "argument_size_in_bytes", 0) or 0)
    out = int(getattr(mem, "output_size_in_bytes", 0) or 0)
    tmp = int(getattr(mem, "temp_size_in_bytes", 0) or 0)
    est = arg + out + 2 * tmp
    return {
        "arg_bytes": arg,
        "out_bytes": out,
        "temp_bytes": tmp,
        "bytes_per_step": est,
        "bytes_per_step_lo": int(est / MEM_EST_INTERVAL),
        "bytes_per_step_hi": int(est * MEM_EST_INTERVAL),
    }


def workload_sims(lanes: int, virtual_secs: float = 10.0,
                  client_rate: float = 0.1) -> dict:
    """name -> (BatchedSim, lanes, max_steps) for every device workload,
    at the SAME configs bench.py sweeps (the per-workload roofline must
    describe the step the bench actually runs)."""
    import os
    import sys

    try:
        import bench as benchmod
    except ImportError:  # invoked as `python benches/roofline.py`
        sys.path.insert(
            0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        import bench as benchmod
    from madsim_tpu.tpu import BatchedSim, chain_workload, make_raft_spec
    from madsim_tpu.tpu.kv import kv_workload
    from madsim_tpu.tpu.paxos import paxos_workload
    from madsim_tpu.tpu.twopc import twopc_workload

    raft_spec = make_raft_spec(
        n_nodes=5, client_rate=client_rate, log_capacity=16
    )
    raft_cfg = benchmod.raft_bench_config(virtual_secs)
    kv = kv_workload(virtual_secs=virtual_secs)
    tp = twopc_workload(virtual_secs=virtual_secs)
    px = paxos_workload(virtual_secs=virtual_secs)
    ch = chain_workload(virtual_secs=virtual_secs)
    return {
        "raft": (BatchedSim(raft_spec, raft_cfg), lanes,
                 int(virtual_secs * 600) + 2000),
        "kv": (BatchedSim(kv.spec, kv.config), lanes,
               int(virtual_secs * 1200) + 2000),
        "twopc": (BatchedSim(tp.spec, tp.config), lanes,
                  int(virtual_secs * 1600) + 2000),
        "paxos": (BatchedSim(px.spec, px.config), lanes,
                  int(virtual_secs * 1600) + 2000),
        "chain": (BatchedSim(ch.spec, ch.config), lanes,
                  int(virtual_secs * 2400) + 2000),
    }


def workload_roofline_row(sim, lanes: int, bw_gbs: float, scan: int = 300,
                          warm_steps: int = 200, timed: bool = True) -> dict:
    """One per-workload roofline row: resident state bytes, the
    memory-analysis bytes/step estimate (+ honesty interval), and — when
    `timed` — the measured step time with achieved bandwidth and the
    carry floor (state read+write at attainable bandwidth: the step's
    hard lower bound; step_over_floor says how far above it the step
    runs, i.e. how much headroom intermediates still cost)."""
    import jax
    import jax.numpy as jnp

    state = sim.run_steps(sim.init(jnp.arange(lanes)), warm_steps)
    jax.block_until_ready(state)
    mem = mem_bytes_per_step(sim, state)
    sbytes = state_bytes(state)
    cb = carry_bytes(state)
    # the carry floor in BYTES: the while_loop carry (hot + cold) is read
    # and written every step; the loop-invariant const tree is read-only
    # and excluded (r8 — that exclusion is the point of the split)
    floor_bytes = 2 * (cb["hot_bytes"] + cb["cold_bytes"])
    floor_ms = floor_bytes / (bw_gbs * 1e9) * 1e3
    row = {
        "lanes": lanes,
        "state_bytes": sbytes,
        "state_bytes_per_lane": round(sbytes / lanes, 1),
        **cb,
        "bytes_per_step": mem["bytes_per_step"],
        "bytes_per_step_lo": mem["bytes_per_step_lo"],
        "bytes_per_step_hi": mem["bytes_per_step_hi"],
        "carry_floor_bytes": floor_bytes,
        # the layout-budget headline (asserted by bench_smoke): how many
        # times the carry's unavoidable read+write the step's estimated
        # traffic is — 1.0 would mean zero intermediate HBM traffic
        "est_over_floor": round(mem["bytes_per_step"] / floor_bytes, 2),
        "carry_floor_ms": round(floor_ms, 3),
    }
    if timed:
        ms = time_step_ms(sim, state, scan, lanes=lanes,
                          warm_steps=warm_steps)
        row.update({
            "step_ms": round(ms, 3),
            "achieved_gbs": round(
                mem["bytes_per_step"] / (ms / 1e3) / 1e9, 1
            ),
            "pct_of_attainable": round(
                mem["bytes_per_step"] / (ms / 1e3) / 1e9 / bw_gbs * 100, 1
            ),
            # the conservative utilization claim (ISSUE 6 bar): achieved
            # bandwidth computed from the LO-bound bytes estimate
            "pct_of_attainable_lo": round(
                mem["bytes_per_step_lo"] / (ms / 1e3) / 1e9 / bw_gbs * 100,
                1,
            ),
            "step_over_floor": round(ms / floor_ms, 2),
        })
    return row


def per_workload_roofline(lanes: int = 32768, scan: int = 300,
                          timed: bool = True) -> dict:
    """The per-workload roofline table (r6): one row per device workload,
    so 'bandwidth-bound' is a per-workload number and a trailing workload
    shows WHERE it trails (state bytes? bytes/step? utilization?)."""
    bw = measure_copy_bw_gbs()
    rows = {}
    for name, (sim, wl_lanes, _steps) in workload_sims(lanes).items():
        rows[name] = workload_roofline_row(
            sim, wl_lanes, bw, scan=scan, timed=timed
        )
    return {"attainable_hbm_gbs": round(bw, 1), "rows": rows}


def _spread_mix_sim(virtual_secs: float):
    """The 10x-horizon-spread workload mix's sim (shared by
    refill_occupancy and mesh_scaling): raft under a crash+loss plan.
    ONE definition lives in madsim_tpu.tune — the r13 tuner measures the
    same mix these tables report on, so the two can never drift onto
    different workloads."""
    from madsim_tpu.tune import spread_mix_sim

    return spread_mix_sim(virtual_secs)


def _spread_ctl_rows(h):
    """Per-admission TriageCtl rows for a horizon column `h` (int64 us)."""
    from madsim_tpu.tune import spread_ctl_from_h

    return spread_ctl_from_h(h)


def mesh_scaling(
    lanes: int = 16, waves: int = 16, spread: int = 10,
    long_every: int = 8, virtual_secs: float = 1.0,
    device_counts=(1, 2, 4, 8), max_steps: int = 50_000,
) -> dict:
    """The multi-chip fleet's headline table (r10, docs/multichip.md):
    the sharded refill sweep on the 10x horizon-spread mix at 1/2/4/8
    devices with EQUAL per-device lanes and equal per-device queue depth
    (admissions scale with the device count). Per row: seeds/s (wall —
    hardware-dependent), per-device occupancy, and the aggregate
    LANE-STEP THROUGHPUT per sweep iteration (busy-lane-steps / max
    device iters — the hardware-independent scaling number: one device
    caps at `lanes` per iteration, D devices at D * lanes).
    `scaling_vs_1dev` on the D-device row is that number over the
    1-device row's; the multichip smoke asserts >= 6x at D = 8.
    Device counts beyond the visible device count are skipped."""
    import numpy as np

    import jax
    from madsim_tpu.tpu.engine import (
        refill_results, refill_results_sharded,
    )

    sim, horizon = _spread_mix_sim(virtual_secs)
    devs = jax.devices()
    rows = []
    base_tp = None
    for D in device_counts:
        if D > len(devs):
            continue
        A = lanes * waves * D
        seeds = np.arange(A, dtype=np.uint32)
        h = np.where(
            np.arange(A) % long_every == 0, horizon, horizon // spread
        ).astype(np.int64)
        ctl = _spread_ctl_rows(h)
        t0 = time.perf_counter()
        if D == 1:
            st = sim.run_refill(
                seeds, lanes=lanes, max_steps=max_steps, ctl=ctl
            )
            res = refill_results(st)
            per_dev = [{
                "iters": res["iters"],
                "busy_lane_steps": res["busy_lane_steps"],
                "total_lane_steps": res["total_lane_steps"],
                "occupancy": res["occupancy"],
            }]
            tp = res["busy_lane_steps"] / max(res["iters"], 1)
        else:
            mesh = jax.sharding.Mesh(np.array(devs[:D]), ("seeds",))
            st = sim.run_refill_sharded(
                seeds, lanes=lanes, mesh=mesh, max_steps=max_steps,
                ctl=ctl,
            )
            res = refill_results_sharded(st, admissions=A)
            per_dev = res["per_device"]
            tp = res["lane_steps_per_iter"]
        wall_s = time.perf_counter() - t0
        if base_tp is None:
            base_tp = tp
        rows.append({
            "devices": D,
            "admissions": A,
            "lanes_per_device": lanes,
            "seeds_per_sec": round(A / max(wall_s, 1e-9), 1),
            "wall_ms": round(wall_s * 1e3, 1),
            "occupancy": round(float(res["occupancy"]), 4),
            "per_device_occupancy": [
                round(float(p["occupancy"]), 4) for p in per_dev
            ],
            "lane_steps_per_iter": round(tp, 2),
            "scaling_vs_1dev": round(tp / max(base_tp, 1e-9), 2),
        })
    return {
        "horizon_spread": spread,
        "long_every": long_every,
        "visible_devices": len(devs),
        "rows": rows,
    }


def refill_occupancy(
    lanes: int = 256, waves: int = 8, spread: int = 10,
    long_every: int = 8, virtual_secs: float = 2.0,
    max_steps: int = 50_000,
) -> dict:
    """The continuous-batching headline metric (r9): LANE OCCUPANCY —
    busy-lane-steps / total-lane-steps per dispatch — on a synthetic
    workload mix with a `spread`x horizon spread (one long admission per
    `long_every`, the ddmin-probe / short-mutant shape), refill vs the
    chunked path on the SAME admissions. Also reports the lane-step
    advantage: how many total lane-steps the chunked path burns per
    refill lane-step for identical per-seed results (wall-clock-free, so
    the number is hardware-independent; the wall ratio follows it once
    the step is bandwidth-bound). Reported into BENCH by bench.py and
    asserted >= 0.9 occupancy by `make refill-smoke`."""
    import numpy as np

    from madsim_tpu.tpu.engine import refill_results

    sim, horizon = _spread_mix_sim(virtual_secs)
    A = lanes * waves
    seeds = np.arange(A, dtype=np.uint32)
    h = np.where(
        np.arange(A) % long_every == 0, horizon, horizon // spread
    ).astype(np.int64)

    def ctl_rows(sel):
        return _spread_ctl_rows(h[sel])

    all_rows = ctl_rows(np.ones((A,), bool))
    t0 = time.perf_counter()
    d0 = sim.dispatch_count
    st = sim.run_refill(seeds, lanes=lanes, max_steps=max_steps,
                        ctl=all_rows)
    res = refill_results(st)
    refill_ms = (time.perf_counter() - t0) * 1e3
    refill_disp = sim.dispatch_count - d0

    chunk_busy = chunk_total = 0
    t0 = time.perf_counter()
    d0 = sim.dispatch_count
    for off in range(0, A, lanes):
        sel = np.zeros((A,), bool)
        sel[off:off + lanes] = True
        stc = sim.run(seeds[off:off + lanes], max_steps=max_steps,
                      dispatch_steps=max_steps, ctl=ctl_rows(sel))
        steps = np.asarray(stc.steps, np.int64)
        chunk_busy += int(steps.sum())
        chunk_total += int(steps.max(initial=0)) * steps.shape[0]
    chunked_ms = (time.perf_counter() - t0) * 1e3
    chunked_disp = sim.dispatch_count - d0

    return {
        "lanes": lanes,
        "admissions": A,
        "horizon_spread": spread,
        "long_every": long_every,
        "occupancy_refill": round(float(res["occupancy"]), 4),
        "occupancy_chunked": round(chunk_busy / max(chunk_total, 1), 4),
        "busy_lane_steps": res["busy_lane_steps"],
        "total_lane_steps_refill": res["total_lane_steps"],
        "total_lane_steps_chunked": chunk_total,
        # chunked lane-steps burned per refill lane-step, same results
        "lane_step_advantage": round(
            chunk_total / max(res["total_lane_steps"], 1), 2
        ),
        "dispatches_refill": refill_disp,
        "dispatches_chunked": chunked_disp,
        "refill_wall_ms": round(refill_ms, 1),
        "chunked_wall_ms": round(chunked_ms, 1),
    }


def step_cost(sim, state):
    """XLA cost analysis of the compiled single-step program."""
    compiled = compile_sweep_step(sim, state)
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    return {
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        "flops": float(ca.get("flops", 0.0)),
    }


def time_step_ms(sim, state, scan: int, reps: int = 3, lanes: int = 0,
                 warm_steps: int = 200) -> float:
    """Median per-step ms over `reps` fresh-seed scan chunks, through the
    shared measurement discipline (madsim_tpu.measure.time_scan_ms:
    fresh seeds per rep, the EXACT (shape, scan) program warmed before
    timing). `state` is accepted for caller symmetry; the discipline
    rebuilds its own settled states from the rep index, settled
    `warm_steps` deep — the SAME depth the caller's accounting state
    used, so timing and bytes accounting describe one regime."""
    del state  # the discipline derives every rep's state from its index
    from madsim_tpu.measure import time_scan_ms

    return time_scan_ms(
        sim.init, sim.run_steps, lanes, scan=scan, warm_steps=warm_steps,
        rounds=reps,
    )


def roofline(lanes: int = 32768, scan: int = 300, variants: bool = True) -> dict:
    import dataclasses

    import jax.numpy as jnp

    import bench as benchmod
    from madsim_tpu.tpu import BatchedSim, make_raft_spec
    from madsim_tpu.tpu.spec import Outbox

    spec = make_raft_spec(n_nodes=5, client_rate=0.1)
    cfg = benchmod.raft_bench_config(10.0)
    sim = BatchedSim(spec, cfg)
    state = sim.run_steps(sim.init(jnp.arange(lanes)), 200)

    bw = measure_copy_bw_gbs()
    cost = step_cost(sim, state)
    sbytes = state_bytes(state)
    cb = carry_bytes(state)
    hlo = hlo_hbm_bytes(sim, state)
    mem = mem_bytes_per_step(sim, state)
    ms = time_step_ms(sim, state, scan, lanes=lanes)
    floor_bytes = 2 * (cb["hot_bytes"] + cb["cold_bytes"])

    out = {
        "attainable_hbm_gbs": round(bw, 1),
        "step_ms": round(ms, 3),
        "step_bytes_accessed": cost["bytes_accessed"],
        "step_flops": cost["flops"],
        "state_bytes": sbytes,
        **cb,
        "carry_floor_bytes": floor_bytes,
        "est_over_floor": round(mem["bytes_per_step"] / floor_bytes, 2),
        # the headline estimate: XLA buffer assignment (arg + out +
        # 2*temp) with its +-20% honesty interval; the HLO per-op model
        # below is kept as a diagnostic (it systematically double-counts
        # fusion boundaries — see mem_bytes_per_step)
        "bytes_per_step": mem["bytes_per_step"],
        "bytes_per_step_lo": mem["bytes_per_step_lo"],
        "bytes_per_step_hi": mem["bytes_per_step_hi"],
        "hlo_model": hlo,
        "achieved_gbs": round(
            mem["bytes_per_step"] / (ms / 1e3) / 1e9, 1
        ),
        "pct_of_attainable": round(
            mem["bytes_per_step"] / (ms / 1e3) / 1e9 / bw * 100, 1
        ),
        "pct_of_attainable_lo": round(
            mem["bytes_per_step_lo"] / (ms / 1e3) / 1e9 / bw * 100, 1
        ),
        "arith_intensity_flops_per_byte": round(
            cost["flops"] / max(mem["bytes_per_step"], 1), 3
        ),
    }

    if variants:
        # ablation attribution, bytes AND ms per ablated phase
        def id_on_message(s, nid, src, kind, payload, now, key):
            E = spec.max_out_msg
            return (
                s,
                Outbox(
                    valid=jnp.zeros((E,), jnp.bool_),
                    dst=jnp.zeros((E,), jnp.int32),
                    kind=jnp.zeros((E,), jnp.int32),
                    payload=jnp.zeros((E, spec.payload_width), jnp.int32),
                ),
                jnp.int32(-1),
            )

        def id_on_timer(s, nid, now, key):
            E = spec.max_out
            return (
                s,
                Outbox(
                    valid=jnp.zeros((E,), jnp.bool_),
                    dst=jnp.zeros((E,), jnp.int32),
                    kind=jnp.zeros((E,), jnp.int32),
                    payload=jnp.zeros((E, spec.payload_width), jnp.int32),
                ),
                now + 50_000,
            )

        def id_on_event(s, nid, src, kind, payload, now, key):
            E = spec.max_out
            return (
                s,
                Outbox(
                    valid=jnp.zeros((E,), jnp.bool_),
                    dst=jnp.zeros((E,), jnp.int32),
                    kind=jnp.zeros((E,), jnp.int32),
                    payload=jnp.zeros((E, spec.payload_width), jnp.int32),
                ),
                jnp.where(kind == -1, now + 50_000, jnp.int32(-1)),
            )

        # the ablated trio is internally consistent (same identity
        # behavior); the stale-wrapper guard requires it to be visible
        id_on_message.__wraps_event__ = id_on_event
        id_on_timer.__wraps_event__ = id_on_event

        ablations = {
            "no_handlers": dataclasses.replace(
                spec, on_message=id_on_message, on_timer=id_on_timer,
                on_event=id_on_event,
            ),
            "no_invariants": dataclasses.replace(
                spec,
                check_invariants=lambda ns, alive, now: jnp.bool_(True),
            ),
        }
        for name, aspec in ablations.items():
            asim = BatchedSim(aspec, cfg)
            astate = asim.run_steps(asim.init(jnp.arange(lanes)), 200)
            acost = step_cost(asim, astate)
            ams = time_step_ms(asim, astate, scan, lanes=lanes)
            out[name] = {
                "step_ms": round(ams, 3),
                "bytes_accessed": acost["bytes_accessed"],
                "attrib_ms": round(out["step_ms"] - ams, 3),
                "attrib_bytes": cost["bytes_accessed"] - acost["bytes_accessed"],
            }
    return out


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--lanes", type=int, default=32768)
    parser.add_argument("--scan", type=int, default=300)
    parser.add_argument("--no-variants", action="store_true")
    parser.add_argument(
        "--per-workload", action="store_true",
        help="emit one roofline row per device workload instead of the "
        "headline-raft deep dive",
    )
    parser.add_argument(
        "--occupancy", action="store_true",
        help="emit the continuous-batching lane-occupancy row (refill vs "
        "chunked on a 10x horizon-spread mix) instead of the deep dive",
    )
    parser.add_argument(
        "--kernels", action="store_true",
        help="emit the per-fused-kernel HBM attribution of the headline "
        "raft step (bytes + estimated time share per kernel) instead of "
        "the deep dive",
    )
    args = parser.parse_args()
    if args.occupancy:
        print(json.dumps(refill_occupancy()), flush=True)
        return
    if args.kernels:
        sims = workload_sims(args.lanes)
        sim, lanes, _steps = sims["raft"]
        print(
            json.dumps({"kernel_rows": workload_kernel_rows(sim, lanes)}),
            flush=True,
        )
        return
    if args.per_workload:
        print(json.dumps(per_workload_roofline(args.lanes, args.scan)),
              flush=True)
        return
    print(
        json.dumps(
            roofline(args.lanes, args.scan, variants=not args.no_variants)
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
