"""oracle-smoke: <60s CPU gate for the schedule-matched differential oracle.

The oracle's value proposition (docs/oracle.md) is one sentence — "any
surface where the host-applied fault stream drifts from the pure per-seed
schedule is a first-class bug" — so this smoke proves both directions:

  * MATCH: a small raft chaos sweep (all message clauses + crash +
    partition + skew) replays schedule-matched on the host twin with
    ZERO divergences on the shipped tree, and non-vacuously so — every
    lane must consume schedule events, coin draws, skewed nodes and
    lineage edges;
  * FIRE: the planted host/device semantic skew
    (MADSIM_TPU_ORACLE_PLANT=reorder_window_off_by_one, an off-by-one in
    the host's reorder-window span) makes the SAME lane diverge, the
    first divergent event is the reorder-window draw anchored into the
    host lineage DAG, and ddmin shrinks the lane to the reorder clause
    alone — the oracle is never vacuously green.

Wall times are printed for eyes only. Usage:
python benches/oracle_smoke.py  (or `make oracle-smoke`)
Exit code != 0 on any assertion failure; prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SEEDS = 6
N_NODES = 5
HORIZON_US = 2_000_000


def main() -> None:
    t0 = time.perf_counter()
    from madsim_tpu import nemesis as nem
    from madsim_tpu import oracle

    assert os.environ.get(nem.PLANT_ENV, "") == "", (
        f"{nem.PLANT_ENV} is set — the MATCH leg would be testing the plant"
    )
    plan = nem.FaultPlan(name="oracle-smoke", clauses=(
        nem.Crash(interval_lo_us=400_000, interval_hi_us=1_500_000,
                  down_lo_us=200_000, down_hi_us=800_000),
        nem.Partition(interval_lo_us=500_000, interval_hi_us=1_800_000,
                      heal_lo_us=300_000, heal_hi_us=1_000_000),
        nem.MsgLoss(rate=0.05),
        nem.Duplicate(rate=0.05),
        nem.Reorder(rate=0.15, window_us=40_000),
        nem.ClockSkew(max_ppm=30_000),
        nem.Reconfig(interval_lo_us=600_000, interval_hi_us=1_600_000,
                     down_lo_us=300_000, down_hi_us=900_000),
    ))

    # -- MATCH: the shipped tree replays schedule-matched, zero drift ----
    draws = events = edges = 0
    for seed in range(SEEDS):
        rep = oracle.check_seed(
            "raft5", plan, seed, HORIZON_US, n_nodes=N_NODES,
            loss_rate=0.1, repeats=2,
        )
        assert not rep.diverged, rep.render()
        assert rep.schedule_events > 0 and rep.draws > 0, rep.render()
        assert rep.skew_nodes > 0 and rep.lineage_edges > 0, rep.render()
        draws += rep.draws
        events += rep.schedule_events
        edges += rep.lineage_edges
    t_match = time.perf_counter() - t0

    # -- FIRE: the planted skew must be caught, localized, and shrunk ----
    t1 = time.perf_counter()
    os.environ[nem.PLANT_ENV] = nem.PLANT_REORDER_OFF_BY_ONE
    try:
        rep = oracle.check_seed(
            "raft5", plan, 3, HORIZON_US, n_nodes=N_NODES, repeats=1,
        )
        assert rep.diverged, "planted reorder off-by-one did not fire"
        first = rep.first
        assert first.kind == "coin" and first.site == "reorder_extra", (
            f"first divergent event should be the reorder-window draw, "
            f"got {first.kind}/{first.site}"
        )
        assert first.slice_text, "divergence not anchored to a delivery"
        sr = oracle.shrink_divergence(
            "raft5", plan, 3, HORIZON_US, n_nodes=N_NODES,
        )
        assert sr.kept_atoms == [("reorder", None)], (
            f"ddmin should isolate the reorder clause, kept {sr.kept_atoms}"
        )
        assert sr.bundle.violation_kind == "divergence"
        assert sr.bundle.causal and sr.bundle.causal.get("sha")
    finally:
        del os.environ[nem.PLANT_ENV]
    t_fire = time.perf_counter() - t1

    print(json.dumps({
        "oracle_smoke": "ok",
        "seeds_matched": SEEDS,
        "schedule_events": events,
        "coin_draws": draws,
        "lineage_edges": edges,
        "planted_first_divergence": rep.first.detail,
        "shrunk_to": [list(a) for a in sr.kept_atoms],
        "shrink_replays": sr.dispatches,
        "wall_s": {
            "match": round(t_match, 1),
            "fire": round(t_fire, 1),
            "total": round(time.perf_counter() - t0, 1),
        },
    }))


if __name__ == "__main__":
    main()
