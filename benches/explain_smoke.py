"""explain-smoke: <60s warm causal-explainability gate for CI.

The r12 lineage plane's whole value proposition is one sentence — "the
farm can say WHICH chain of deliveries broke the invariant" — so this
smoke walks the full path on the planted deposed-leader re-stamp bug
(docs/bugs_found.md #1) and asserts the explanation is the right one:

  * SWEEP: a 48-seed chaotic sweep of the planted config finds >= 2
    violating seeds (the seed-dense regime campaign dedup collapses);
  * SLICE: the first witness replays with BatchedSim(lineage=True); its
    happens-before DAG decodes and VERIFIES (every u16 sent_eid stamp
    resolves to a real send event; in-jit Lamport clocks == the pure
    edge recomputation), and the violation's causal slice NAMES the
    re-stamp delivery chain — the anchor is the APPEND delivery that
    exposed the corrupted committed prefix, with further APPEND links
    behind it;
  * SKELETON: a second witness's slice aligns with the first into a
    nonempty shared event skeleton containing that APPEND mechanism —
    identical whichever witness order the fold runs in terms of content
    hash (seed-sorted, as campaign anatomy does);
  * BUDGET: the lineage plane's carry cost on this config stays under
    the 15% bench_smoke ceiling (re-asserted here so the explain gate is
    self-contained).

Wall times are printed for eyes only. Usage:
python benches/explain_smoke.py  (or `make explain-smoke`)
Exit code != 0 on any assertion failure; prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

SEEDS = 48
LINEAGE_OVERHEAD_PCT_MAX = 15.0


def main() -> None:
    t0 = time.perf_counter()
    import jax.numpy as jnp
    import numpy as np

    from madsim_tpu import causal
    from madsim_tpu.tpu.engine import BatchedSim
    from ttfb import restamp_workload

    wl = restamp_workload()

    # -- sweep: find the witnesses --------------------------------------
    sim = BatchedSim(wl.spec, wl.config)
    st = sim.run(jnp.arange(SEEDS, dtype=jnp.uint32), max_steps=20_000)
    viol = np.nonzero(np.asarray(st.violated))[0]
    steps = np.asarray(st.violation_step)
    assert viol.size >= 2, f"planted bug found on only {viol.size} seeds"
    t_sweep = time.perf_counter() - t0

    # -- slice: explain the first witness -------------------------------
    t1 = time.perf_counter()
    wit = [(int(s), int(steps[s])) for s in viol[:2]]
    slices = []
    for seed, step in wit:
        g, sl = causal.explain(
            wl.spec, wl.config, seed, max_steps=step + 2,
        )
        assert g.violation is not None
        slices.append(sl)
    anchor = slices[0].chain[-1]
    assert anchor.kind == "deliver" and anchor.msg_name == "APPEND", (
        f"anchor must be the re-stamped APPEND delivery, got {anchor}"
    )
    labels = [causal.slice_labels(s) for s in slices]
    appends = [l for l in labels[0] if l.startswith("deliver:APPEND:")]
    assert len(appends) >= 2, (
        f"slice must name the re-stamp delivery chain, got {labels[0][-8:]}"
    )
    t_slice = time.perf_counter() - t1

    # -- skeleton: align the two witnesses ------------------------------
    skel = causal.skeleton(labels)
    assert skel, "two witnesses of one bug class must share a skeleton"
    assert any(l.startswith("deliver:APPEND:") for l in skel), (
        f"skeleton must keep the APPEND mechanism, got {skel[-8:]}"
    )

    # -- budget: lineage carry cost under the ceiling -------------------
    import roofline as rl

    def carry_per_lane(lineage: bool) -> float:
        s = BatchedSim(wl.spec, wl.config, lineage=lineage)
        cb = rl.carry_bytes(s.init(jnp.arange(8, dtype=jnp.uint32)))
        return (cb["hot_bytes"] + cb["cold_bytes"]) / 8

    base, lin = carry_per_lane(False), carry_per_lane(True)
    lin_pct = round(100.0 * (lin - base) / base, 2)
    assert lin_pct <= LINEAGE_OVERHEAD_PCT_MAX, (
        f"lineage carry +{lin_pct}% > {LINEAGE_OVERHEAD_PCT_MAX}% budget"
    )

    print(json.dumps({
        "explain_smoke": "ok",
        "violating_seeds": int(viol.size),
        "anchor": str(anchor),
        "chain_len": len(slices[0].chain),
        "cone_size": slices[0].cone_size,
        "depth": slices[0].depth,
        "skeleton_len": len(skel),
        "noise": [len(l) - len(skel) for l in labels],
        "lineage_overhead_pct": lin_pct,
        "wall_s": {
            "sweep": round(t_sweep, 1),
            "explain": round(t_slice, 1),
            "total": round(time.perf_counter() - t0, 1),
        },
    }))


if __name__ == "__main__":
    main()
