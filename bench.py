"""Headline benchmark: seeds/sec fuzzing 5-node Raft (BASELINE.json metric).

Compares the TPU batched engine (thousands of seed lanes per jitted step)
against the reference execution model: one full simulation per seed on the
host executor (the thread-per-seed CPU baseline,
reference runtime/builder.rs:118-136). The honest denominator is the
compiled C++ single-core fuzzer (see BASELINE.md "North star, restated").

The sweep goes through the production multi-device path (`run_batch`-style
lane mesh over every visible device); on this environment that is one chip,
and `vs_baseline` is per-chip by construction.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "seeds/s", "vs_baseline": N, ...}

Measurement notes (hard-won on the remote-tunnel TPU): every timed rep uses
FRESH seeds — the tunnel relay caches identical dispatches — and the median
of 3 reps drops contention outliers in either direction.
"""

from __future__ import annotations

import argparse
import json
import time

import jax.numpy as jnp


def raft_bench_config(virtual_secs: float):
    from madsim_tpu.tpu import SimConfig

    return SimConfig(
        horizon_us=int(virtual_secs * 1e6),
        # slot budget measured for ZERO overflow (headline config must drop
        # NOTHING the network didn't roll to drop): the fused raft spec
        # shares outbox rows between broadcasts and replies, placement is
        # NODE-POOLED (a send takes the i-th free slot of its node's whole
        # 8-slot budget), and ack bursts alternate reply rows
        # (RaftState.reply_parity). Budget sweep (depth x N + spare):
        # SK=6 dropped 35/81M sends, SK=7 dropped 1/81M, SK=8 dropped 0
        # across the r5 hunts (and non-monotone step times across SK —
        # TPU minor-dim tiling — made SK=8 the fastest clean point too).
        msg_depth_msg=1,
        msg_spare_slots=3,
        loss_rate=0.10,
        crash_interval_lo_us=500_000,
        crash_interval_hi_us=3_000_000,
        restart_delay_lo_us=300_000,
        restart_delay_hi_us=2_000_000,
        # partition chaos on: random bipartitions every 0.3-1.5s, healing
        # after 0.5-2s (the host baseline runs the same partition schedule
        # rate via fuzz_one_seed(partitions=True))
        partition_interval_lo_us=300_000,
        partition_interval_hi_us=1_500_000,
        partition_heal_lo_us=500_000,
        partition_heal_hi_us=2_000_000,
    )


def _timed_median_of_3(sim, lanes: int, max_steps: int, mesh=None):
    """Warm-compile, then time 3 fresh-seed reps and take the median wall
    — the shared measurement discipline (madsim_tpu.measure.time_sweep:
    the tunnel relay caches identical dispatches, so every rep derives
    fresh seeds from its index, and the median drops one contention
    outlier in either direction)."""
    from madsim_tpu.measure import time_sweep

    return time_sweep(
        lambda seeds: sim.run(
            jnp.asarray(seeds), max_steps=max_steps, mesh=mesh
        ),
        lanes,
    )


def bench_tpu(lanes: int, virtual_secs: float, client_rate: float) -> dict:
    import jax

    from madsim_tpu.tpu import BatchedSim, make_raft_spec, summarize
    from madsim_tpu.tpu.batch import resolve_mesh

    # log_capacity 16: the circular window + compaction + InstallSnapshot
    # keep unbounded writes flowing through 16 slots (saturation metric
    # guards the claim — stays 0 at this config); window bytes are a top
    # handler cost, and 16 measured ~5% faster than 24 with no lost work
    spec = make_raft_spec(n_nodes=5, client_rate=client_rate, log_capacity=16)
    sim = BatchedSim(spec, raft_bench_config(virtual_secs))
    mesh = resolve_mesh("auto")  # production path: every visible device
    n_devices = int(mesh.devices.size) if mesh is not None else 1
    max_steps = int(virtual_secs * 600) + 2000  # generous event budget
    wall, state = _timed_median_of_3(sim, lanes, max_steps, mesh=mesh)
    s = summarize(state, spec)
    import numpy as np

    steps_run = int(np.asarray(state.steps).max())
    return {
        "wall_s": wall,
        "seeds_per_sec": lanes / wall,
        "events_per_sec": s["total_events"] / wall,
        "step_ms": wall / max(steps_run, 1) * 1e3,
        "steps_run": steps_run,
        "n_devices": n_devices,
        "summary": s,
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
    }


def bench_step_breakdown(lanes: int, virtual_secs: float,
                         client_rate: float) -> dict:
    """Where the step time goes: full vs spec-handlers-ablated vs
    invariants-ablated (VERDICT r3 weak #1 asked for the attribution)."""
    import dataclasses

    from madsim_tpu.tpu import BatchedSim, make_raft_spec
    from madsim_tpu.tpu.spec import Outbox

    spec = make_raft_spec(n_nodes=5, client_rate=client_rate, log_capacity=16)
    cfg = raft_bench_config(virtual_secs)

    def id_on_message(s, nid, src, kind, payload, now, key):
        E = spec.max_out_msg
        out = Outbox(
            valid=jnp.zeros((E,), jnp.bool_),
            dst=jnp.zeros((E,), jnp.int32),
            kind=jnp.zeros((E,), jnp.int32),
            payload=jnp.zeros((E, spec.payload_width), jnp.int32),
        )
        return s, out, jnp.int32(-1)

    def id_on_timer(s, nid, now, key):
        E = spec.max_out
        out = Outbox(
            valid=jnp.zeros((E,), jnp.bool_),
            dst=jnp.zeros((E,), jnp.int32),
            kind=jnp.zeros((E,), jnp.int32),
            payload=jnp.zeros((E, spec.payload_width), jnp.int32),
        )
        return s, out, now + 50_000

    def id_on_event(s, nid, src, kind, payload, now, key):
        # fused identity (keeps the ablated variant on the same engine
        # path / candidate layout as the full fused spec)
        E = spec.max_out
        out = Outbox(
            valid=jnp.zeros((E,), jnp.bool_),
            dst=jnp.zeros((E,), jnp.int32),
            kind=jnp.zeros((E,), jnp.int32),
            payload=jnp.zeros((E, spec.payload_width), jnp.int32),
        )
        return s, out, jnp.where(kind == -1, now + 50_000, jnp.int32(-1))

    # the ablated trio is internally consistent (same identity behavior);
    # the stale-wrapper guard requires the derivation to be visible
    id_on_message.__wraps_event__ = id_on_event
    id_on_timer.__wraps_event__ = id_on_event

    variants = {
        "full": BatchedSim(spec, cfg),
        "no_handlers": BatchedSim(
            dataclasses.replace(
                spec, on_message=id_on_message, on_timer=id_on_timer,
                on_event=id_on_event,
            ),
            cfg,
        ),
        "no_invariants": BatchedSim(
            dataclasses.replace(
                spec, check_invariants=lambda ns, alive, now: jnp.bool_(True)
            ),
            cfg,
        ),
    }
    from madsim_tpu.measure import time_scan_ms

    SCAN = 300
    out = {}
    for name, sim in variants.items():
        # the shared scan-on-device discipline: fresh seeds per rep,
        # the exact (shape, SCAN) program warmed before timing
        out[name] = round(
            time_scan_ms(
                sim.init, sim.run_steps, lanes, scan=SCAN, warm_steps=200
            ),
            3,
        )
    return {
        "step_ms_full": out["full"],
        "step_ms_spec_handlers": round(out["full"] - out["no_handlers"], 3),
        "step_ms_invariant_check": round(out["full"] - out["no_invariants"], 3),
    }


def bench_buggify_ab(lanes: int, virtual_secs: float) -> dict:
    """A/B: the heavy-tail delay buggify (net/mod.rs:287-295 analog) on the
    KV linearizability fuzz — extreme stragglers are a distinct bug class,
    and the A/B shows the chaos actually changes what the fuzz explores."""
    import dataclasses

    from madsim_tpu.tpu import BatchedSim, summarize
    from madsim_tpu.tpu.kv import kv_workload

    out = {}
    for tag, rate in (("off", 0.0), ("on", 0.05)):
        wl = kv_workload(virtual_secs=virtual_secs)
        # straggler depth 24: a 1-5 s tail at 5% of a 25 ms-tick heartbeat
        # stream keeps ~6 tails of one send site in flight at once, and the
        # r5 fused kv spec nearly HALVED the candidate count (C 55 -> 30),
        # halving the side pool at a given depth — depth 8 measured 11k
        # drops post-fusion and depth 16 still 73; the side pool must hold
        # tails, not drop them (drops would be unmodeled loss muddying
        # the A/B)
        cfg = dataclasses.replace(
            wl.config, buggify_delay_rate=rate, buggify_depth=24
        )
        sim = BatchedSim(wl.spec, cfg)
        state = sim.run(jnp.arange(lanes), max_steps=int(virtual_secs * 1200) + 2000)
        s = summarize(state, wl.spec)
        out[tag] = {
            "events": s["total_events"],
            "violations": s["violations"],
            "mean_acked_ops": round(s.get("mean_acked_ops", 0.0), 2),
            "overflow": s["total_overflow"],
        }
    return out


def bench_kv(lanes: int, virtual_secs: float) -> dict:
    """Second device protocol: replicated-KV linearizability under
    partitions (BASELINE config #4 / SURVEY §7 step 5). Client histories
    recorded per lane; device oracle = real-time revision monotonicity +
    per-(node,key) watermarks; host oracle = full per-key linearizability
    check over violating lanes (madsim_tpu/tpu/linearize.py)."""
    from madsim_tpu.tpu import BatchedSim, summarize
    from madsim_tpu.tpu.kv import kv_workload

    import numpy as np

    from madsim_tpu.tpu import linearize

    wl = kv_workload(virtual_secs=virtual_secs)
    sim = BatchedSim(wl.spec, wl.config)
    max_steps = int(virtual_secs * 1200) + 2000

    wall, state = _timed_median_of_3(sim, lanes, max_steps)
    s = summarize(state, wl.spec)
    # exact-oracle coverage accounting (VERDICT r4 weak #3): run the
    # Wing-Gong checker over a lane sample and report what fraction of
    # those lanes' ACKED ops received an exact (not just watermark) check
    sample = list(range(0, min(lanes, 128)))
    exact = linearize.check_lanes(state.node, sample)
    acked_sample = float(
        np.asarray(state.node.h_len)[sample].sum()
    )
    s["exact_check"] = {
        "lanes": len(sample),
        "ops_exact_checked": exact["ops_checked"],
        "unmatched_reads": exact["unmatched_reads"],
        "acked_ops": int(acked_sample),
        "fraction_exact": round(
            exact["ops_checked"] / max(acked_sample, 1), 3
        ),
        "violations": exact["violations"],
    }
    return {
        "wall_s": wall,
        "seeds_per_sec": lanes / wall,
        "summary": s,
    }


def bench_twopc(lanes: int, virtual_secs: float) -> dict:
    """Third device protocol: Two-Phase Commit atomicity under the full
    chaos battery (loss + coordinator crashes + partitions)."""
    from madsim_tpu.tpu import BatchedSim, summarize
    from madsim_tpu.tpu.twopc import twopc_workload

    wl = twopc_workload(virtual_secs=virtual_secs)
    sim = BatchedSim(wl.spec, wl.config)
    max_steps = int(virtual_secs * 1600) + 2000

    wall, state = _timed_median_of_3(sim, lanes, max_steps)
    return {
        "wall_s": wall,
        "seeds_per_sec": lanes / wall,
        "summary": summarize(state, sim.spec),
    }


def bench_roofline(lanes: int, virtual_secs: float, client_rate: float) -> dict:
    """PER-WORKLOAD roofline accounting (r6; the r5 version covered raft
    only and bracketed bytes/step 3.7x wide): for EVERY device workload,
    resident state bytes, the `compiled.memory_analysis()`-based bytes/step
    estimate with its single +-20% honesty interval (bracket 1.44x), the
    measured step time, achieved bandwidth, and the carry floor — so each
    workload's 'bandwidth-bound' claim (or its absence) is a number, and a
    trailing workload shows WHERE it trails. Uses benches/roofline.py's
    measured-methodology probes (marginal bandwidth, buffer-assignment
    traffic model)."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "benches"))
    try:
        import roofline as rl

        bw = rl.measure_copy_bw_gbs()
        rows = {}
        sims = rl.workload_sims(lanes, virtual_secs, client_rate)
        for name, (sim, wl_lanes, _steps) in sims.items():
            try:
                rows[name] = rl.workload_roofline_row(
                    sim, wl_lanes, bw, scan=300
                )
            except Exception as e:  # noqa: BLE001 - one row must not
                # take down the table
                rows[name] = {"error": str(e)[:160]}
        raft = rows.get("raft", {})
        # per-fused-kernel HBM attribution of the headline raft step
        # (r13): bytes + estimated time share per top-level kernel — the
        # steering table for the next perf round (BENCH `kernel_rows`)
        try:
            kernel_rows = rl.workload_kernel_rows(sims["raft"][0], lanes)
        except Exception as e:  # noqa: BLE001 - diagnostics only
            kernel_rows = [{"error": str(e)[:160]}]
        return {
            "roofline_attainable_gbs": round(bw, 1),
            "roofline_step_ms": raft.get("step_ms"),
            "roofline_state_bytes": raft.get("state_bytes"),
            # ONE estimate + honesty interval (r6): XLA buffer assignment
            # (args read + outputs written + temps written-then-read),
            # +-20% for multi-read traffic vs on-chip reuse — replaces the
            # r5 lo/hi pair whose ends were 3.7x apart
            "roofline_bytes_per_step": raft.get("bytes_per_step"),
            "roofline_bytes_per_step_lo": raft.get("bytes_per_step_lo"),
            "roofline_bytes_per_step_hi": raft.get("bytes_per_step_hi"),
            "roofline_achieved_gbs": raft.get("achieved_gbs"),
            "roofline_pct_of_attainable": raft.get("pct_of_attainable"),
            "roofline_pct_of_attainable_lo": raft.get(
                "pct_of_attainable_lo"
            ),
            # the carry floor (r8: the hot+cold while_loop carry, NOT the
            # flat state — ConstState rides loop-invariant and is excluded):
            # read+written every step no matter what, the step's hard
            # lower bound on both bytes and time
            "roofline_carry_floor_bytes": raft.get("carry_floor_bytes"),
            "roofline_est_over_floor": raft.get("est_over_floor"),
            "roofline_carry_floor_ms": raft.get("carry_floor_ms"),
            "roofline_step_over_floor": raft.get("step_over_floor"),
            "roofline_rows": rows,
            "kernel_rows": kernel_rows,
            # continuous batching (r9): lane occupancy refill-vs-chunked
            # on a 10x horizon-spread mix + the lane-step advantage
            "refill_occupancy": rl.refill_occupancy(),
            # multi-chip fleet (r10): seeds/s + per-device occupancy +
            # lane-step scaling at 1/2/4/8 devices on the same mix
            # (device counts beyond the visible fleet are skipped)
            "mesh_scaling": rl.mesh_scaling(),
        }
    except Exception as e:  # noqa: BLE001 - diagnostics must not kill BENCH
        return {"roofline_error": str(e)[:200]}
    finally:
        sys.path.pop(0)


def bench_tuned_ab(lanes: int, virtual_secs: float,
                   cache_dir: "str | None" = None) -> dict:
    """Default-vs-tuned A/B per workload (the BENCH `tuned` key, r13):
    the measured autotuner's win as a number. Per named workload, the
    device's tuned entry is resolved from the cache (`make tune`
    populates it; a cold cache triggers a quick Tier-A pass measured
    in-memory — never persisted, so a bench run cannot plant a
    quick-screen entry where consumers expect a full winner), then
    default-vs-tuned `run_batch` walls are
    measured as interleaved fresh-seed medians — the shared discipline,
    so the ratio carries the same credibility as every other BENCH
    number. Tier A only: per-seed results are bit-identical across the
    A/B by the engine's contract (docs/tuning.md)."""
    import dataclasses as dc

    from madsim_tpu import tune as tunemod
    from madsim_tpu.explore import _named_workload
    from madsim_tpu.measure import fresh_seeds, interleaved_medians
    from madsim_tpu.tpu.batch import run_batch
    from madsim_tpu.tpu.engine import BatchedSim

    out = {}
    for name in ("raft", "kv", "twopc", "paxos", "chain"):
        try:
            wl = dc.replace(
                _named_workload(name, virtual_secs, False), host_repro=None
            )
            cfg = wl.config
            # the cache identity is the SPEC name ("raft5") — the same
            # key every tuning="auto" consumer resolves with
            entry = tunemod.load_tuned(
                wl.spec.name, cfg, lanes, dir=cache_dir
            )
            cached = entry is not None
            if entry is None:
                # save=False: the cold-cache fill is a QUICK screen for
                # the A/B table only — persisting it would masquerade as
                # a full `make tune` winner under the exact key every
                # tuning="auto" consumer (and campaign resume-conflict
                # check) reads, so a bench run could break a campaign's
                # resume. The A/B measures the in-memory entry instead.
                entry = tunemod.tune_workload(
                    wl, name, lanes=lanes, n_seeds=lanes, quick=True,
                    cache_dir=cache_dir, save=False,
                )
            tn = dict(entry.dispatch)
            sim = BatchedSim(wl.spec, cfg)

            def sweep(tuning, wl=wl, sim=sim):
                def run(rep: int):
                    run_batch(
                        fresh_seeds(rep, lanes), wl, sim=sim,
                        repro_on_host=False, max_traces=0, tuning=tuning,
                    )
                return run

            default_run = sweep(None)
            tuned_run = sweep(tn or None)
            default_run(0)  # warm both programs outside the timed rounds
            tuned_run(0)
            meds = interleaved_medians(
                {"default": default_run, "tuned": tuned_run}, rounds=3
            )
            out[name] = {
                "default_seeds_per_sec": round(lanes / meds["default"], 2),
                "tuned_seeds_per_sec": round(lanes / meds["tuned"], 2),
                "win_pct": round(
                    (meds["default"] / meds["tuned"] - 1) * 100, 2
                ),
                "dispatch": tn,
                "cached": cached,
                "fallback": entry.fallback,
            }
        except Exception as e:  # noqa: BLE001 - one workload must not
            # take down the table
            out[name] = {"error": f"{type(e).__name__}: {str(e)[:160]}"}
    return out


def bench_ttfb(chunk: int = 1024, max_seeds: int = 8192) -> dict:
    """Time-to-first-bug on the in-tree planted-bug configs (the OTHER
    half of BASELINE.json's metric, measured for the first time in r6):
    wall-clock from a cold runtime to a confirmed violating seed, and on
    to a finished triage ReproBundle. See benches/ttfb.py."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "benches"))
    try:
        import ttfb as ttfb_mod

        return ttfb_mod.ttfb_all(chunk=chunk, max_seeds=max_seeds)
    except Exception as e:  # noqa: BLE001 - diagnostics must not kill BENCH
        return {"ttfb_error": str(e)[:200]}
    finally:
        sys.path.pop(0)


def bench_explore(lanes: int = 256, dispatches: int = 8) -> dict:
    """Explorer vs uniform sweep on the planted-bug configs: union
    coverage per dispatch and dispatches-to-first-bug under the same lane
    budget (the coverage-guided search of docs/explore.md; see
    benches/explore_bench.py)."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "benches"))
    try:
        import explore_bench

        return explore_bench.explore_all(lanes=lanes, dispatches=dispatches)
    except Exception as e:  # noqa: BLE001 - diagnostics must not kill BENCH
        return {"explore_error": str(e)[:200]}
    finally:
        sys.path.pop(0)


def bench_devloop(lanes: int = 16, gens: int = 4, window: int = 2) -> dict:
    """Host loop vs device-resident generation loop (r19): the same
    search both ways on one shared sim — generations/s, blocking syncs
    per generation (device budget: <= 1, one per window), total dispatch
    counts, and report fingerprint equality (see
    benches/explore_bench.devloop_ab, docs/explore.md)."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "benches"))
    try:
        import explore_bench
        import ttfb as ttfb_mod

        factory, _ = ttfb_mod.PLANTED["raft_restamp"]
        return explore_bench.devloop_ab(
            factory(), lanes=lanes, gens=gens, window=window,
        )
    except Exception as e:  # noqa: BLE001 - diagnostics must not kill BENCH
        return {"devloop_error": str(e)[:200]}
    finally:
        sys.path.pop(0)


def bench_paxos(lanes: int, virtual_secs: float) -> dict:
    """Fourth device protocol: single-decree Paxos agreement under the
    full chaos battery (dueling proposers as the steady state)."""
    from madsim_tpu.tpu import BatchedSim, summarize
    from madsim_tpu.tpu.paxos import paxos_workload

    wl = paxos_workload(virtual_secs=virtual_secs)
    sim = BatchedSim(wl.spec, wl.config)
    max_steps = int(virtual_secs * 1600) + 2000

    wall, state = _timed_median_of_3(sim, lanes, max_steps)
    return {
        "wall_s": wall,
        "seeds_per_sec": lanes / wall,
        "summary": summarize(state, sim.spec),
    }


def bench_chain(lanes: int, virtual_secs: float) -> dict:
    """Fifth device protocol: chain replication under loss + crash chaos
    (hop-by-hop acks, retransmission, tail reads)."""
    from madsim_tpu.tpu import BatchedSim, chain_workload, summarize

    wl = chain_workload(virtual_secs=virtual_secs)
    sim = BatchedSim(wl.spec, wl.config)
    max_steps = int(virtual_secs * 2400) + 2000

    wall, state = _timed_median_of_3(sim, lanes, max_steps)
    return {
        "wall_s": wall,
        "seeds_per_sec": lanes / wall,
        "summary": summarize(state, sim.spec),
    }


def bench_telemetry_overhead(
    lanes: int = 256, virtual_secs: float = 0.5, iters: int = 6,
    repeats: int = 3,
) -> dict:
    """Span-wrapped vs bare dispatch loop on the smoke raft workload.

    Telemetry's contract is observe-only AND near-free: the span sites in
    run_batch/explore/triage/serve wrap ms-scale device dispatches with a
    µs-scale perf_counter pair, so enabling capture must cost <2% wall
    (asserted by tests/test_telemetry.py on this same measurement). Both
    loops run the SAME compiled program on the SAME seeds — identical
    device work, only the span machinery differs (per-seed wall varies
    with trajectory length, so fresh-seed A/B would measure seed luck,
    not telemetry) — and min-of-`repeats` damps scheduler noise. Also
    reports the raw per-span cost so the budget is auditable:
    overhead ≈ spans/dispatch x span_us / wall."""
    import numpy as np

    import madsim_tpu.telemetry as telemetry
    from madsim_tpu.tpu import BatchedSim, make_raft_spec

    spec = make_raft_spec(n_nodes=5)
    sim = BatchedSim(spec, raft_bench_config(virtual_secs))
    max_steps = int(virtual_secs * 600) + 500

    def loop() -> None:
        for i in range(iters):
            seeds = np.arange(i * lanes, (i + 1) * lanes, dtype=np.uint32)
            with telemetry.span("dispatch", site="bench"):
                st = sim.run(seeds, max_steps=max_steps)
            with telemetry.span("decode", site="bench"):
                st.violated.block_until_ready()

    telemetry.disable()
    loop()  # warm the compile outside both timed loops
    bare, wrapped = [], []
    for _ in range(repeats):
        telemetry.disable()
        t0 = time.perf_counter()
        loop()
        bare.append(time.perf_counter() - t0)
        telemetry.enable()
        t0 = time.perf_counter()
        loop()
        wrapped.append(time.perf_counter() - t0)
    # per-span machinery cost, measured directly (enabled path)
    telemetry.enable()
    n_micro = 10_000
    t0 = time.perf_counter()
    for _ in range(n_micro):
        with telemetry.span("micro"):
            pass
    span_us = (time.perf_counter() - t0) / n_micro * 1e6
    telemetry.disable()
    bare_s, wrapped_s = min(bare), min(wrapped)
    return {
        "bare_s": round(bare_s, 4),
        "wrapped_s": round(wrapped_s, 4),
        "overhead_pct": round(
            max(wrapped_s - bare_s, 0.0) / bare_s * 100, 3
        ),
        "span_us": round(span_us, 3),
        "spans_per_dispatch": 2,
        "dispatches": iters,
    }


def bench_cpp_baseline(n_seeds: int, virtual_secs: float, client_rate: float) -> dict:
    """The HONEST CPU denominator: a compiled thread-per-seed DES fuzzer
    (native/raft_bench.cpp) running the same protocol + chaos + invariant
    checks as the device spec, single-core — what the reference's compiled
    Rust executor model achieves per core on this workload. Compiled on
    demand with g++ -O2; returns None when no C++ toolchain exists.
    """
    import pathlib
    import shutil
    import subprocess

    src = pathlib.Path(__file__).parent / "madsim_tpu" / "native" / "raft_bench.cpp"
    out = pathlib.Path(__file__).parent / "build" / "raft_bench"
    gxx = shutil.which("g++") or shutil.which("clang++")
    if gxx is None or not src.exists():
        return None
    if not out.exists() or out.stat().st_mtime < src.stat().st_mtime:
        out.parent.mkdir(exist_ok=True)
        r = subprocess.run(
            [gxx, "-O2", "-std=c++17", "-o", str(out), str(src)],
            capture_output=True, text=True,
        )
        if r.returncode != 0:
            return None
    # Denominator-pinning protocol (BASELINE.md "Measurement protocol"):
    # median of 5 isolated runs. The r4 artifact's single biggest weakness
    # was this number swinging 419-837 seeds/s with host contention —
    # pin to one core (taskset, when available), run nothing else
    # concurrently, and REPORT the spread so the headline ratio carries
    # its own error bar.
    cmd = [str(out), str(n_seeds), str(virtual_secs), str(client_rate), "0.1"]
    taskset = shutil.which("taskset")
    if taskset:
        cmd = [taskset, "-c", "0"] + cmd
    rows = []
    for _ in range(5):
        try:
            r = subprocess.run(cmd, capture_output=True, text=True, timeout=600)
            if r.returncode != 0:
                break
            rows.append(json.loads(r.stdout.strip().splitlines()[-1]))
        except (subprocess.TimeoutExpired, ValueError, IndexError):
            # keep any completed reps; missing-toolchain/compile-failure paths
            # degrade to the python_host denominator — never kill the bench
            break
    if not rows:
        return None
    sps = sorted(x["seeds_per_sec"] for x in rows)
    med = sorted(rows, key=lambda x: x["seeds_per_sec"])[(len(rows) - 1) // 2]
    med = dict(med)
    med["reps"] = len(rows)
    med["seeds_per_sec_min"] = round(sps[0], 2)
    med["seeds_per_sec_max"] = round(sps[-1], 2)
    med["spread_pct"] = round(
        (sps[-1] - sps[0]) / max(sps[len(sps) // 2], 1e-9) * 100, 1
    )
    return med


def bench_cpu_baseline(n_seeds: int, virtual_secs: float, client_rate: float) -> dict:
    from madsim_tpu.workloads.raft_host import fuzz_one_seed

    # warm one seed (imports, code paths)
    fuzz_one_seed(
        999_983, virtual_secs=virtual_secs, client_rate=client_rate, partitions=True
    )
    rows = []
    for rep in range(3):  # median of 3, same rep scheme as every other side
        t0 = time.perf_counter()
        events = 0
        for seed in range(rep * n_seeds, (rep + 1) * n_seeds):
            r = fuzz_one_seed(
                seed, virtual_secs=virtual_secs, client_rate=client_rate,
                partitions=True,
            )
            events += r["events"]
        wall = time.perf_counter() - t0
        rows.append({
            "wall_s": wall,
            "seeds_per_sec": n_seeds / wall,
            "events_per_sec": events / wall,
        })
    return sorted(rows, key=lambda x: x["seeds_per_sec"])[1]


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--lanes", type=int, default=32768)
    parser.add_argument("--virtual-secs", type=float, default=10.0)
    parser.add_argument("--cpu-seeds", type=int, default=16)
    # client_rate sized so the TPU spec's fixed-capacity log does NOT
    # saturate within the horizon (10s x 0.1/heartbeat ~ 20 appends < 24
    # capacity) — both backends then run the same protocol work end to end
    parser.add_argument("--client-rate", type=float, default=0.1)
    parser.add_argument("--skip-breakdown", action="store_true")
    parser.add_argument("--skip-ttfb", action="store_true")
    parser.add_argument("--skip-explore", action="store_true")
    parser.add_argument(
        "--skip-tune", action="store_true",
        help="skip the default-vs-tuned A/B (BENCH `tuned` key)",
    )
    parser.add_argument(
        "--skip-devloop", action="store_true",
        help="skip the host-vs-device generation-loop A/B "
        "(BENCH `generations_per_s` key)",
    )
    args = parser.parse_args()

    cpu = bench_cpu_baseline(args.cpu_seeds, args.virtual_secs, args.client_rate)
    cpp = bench_cpp_baseline(
        max(args.cpu_seeds * 16, 256), args.virtual_secs, args.client_rate
    )
    tpu = bench_tpu(args.lanes, args.virtual_secs, args.client_rate)
    # kv and twopc sweep at FULL lanes since r6: the r5 //4 sizing left the
    # chip badly underutilized on exactly the workloads that trailed —
    # twopc runs ~1.4k steps/sweep (raft-like), so its 3.6x per-lane wall
    # gap was mostly idle hardware, not step cost. Lane counts are in the
    # JSON (kv_lanes/twopc_lanes); per-step work is unchanged, so
    # seeds/s remains comparable across rounds as lanes/wall.
    kv = bench_kv(args.lanes, args.virtual_secs)
    twopc = bench_twopc(args.lanes, args.virtual_secs)
    paxos = bench_paxos(args.lanes // 4, args.virtual_secs)
    chain = bench_chain(args.lanes // 4, args.virtual_secs)
    buggify = bench_buggify_ab(args.lanes // 16, args.virtual_secs)
    breakdown = (
        {} if args.skip_breakdown
        else bench_step_breakdown(args.lanes, args.virtual_secs, args.client_rate)
    )
    roofline = (
        {} if args.skip_breakdown
        else bench_roofline(args.lanes, args.virtual_secs, args.client_rate)
    )
    ttfb = {} if args.skip_ttfb else bench_ttfb()
    explore = {} if args.skip_explore else bench_explore()
    devloop = {} if args.skip_devloop else bench_devloop()
    tuned = (
        {} if args.skip_tune
        else bench_tuned_ab(args.lanes, args.virtual_secs)
    )
    telemetry_overhead = bench_telemetry_overhead()

    # vs_baseline is computed against the STRONGEST CPU execution available:
    # the compiled C++ thread-per-seed DES (the reference's execution model)
    # when a toolchain exists, else the Python host runtime. Both
    # denominators are reported; the C++ one is single-core, and the TPU
    # side here is one chip, so vs_baseline reads "chips per core".
    strongest = max(
        cpu["seeds_per_sec"], cpp["seeds_per_sec"] if cpp else 0.0
    )
    result = {
        "metric": "raft5_fuzz_seeds_per_sec",
        "value": round(tpu["seeds_per_sec"], 2),
        "unit": "seeds/s",
        "vs_baseline": round(tpu["seeds_per_sec"] / strongest, 2),
        "baseline_kind": "cpp_compiled_single_core" if cpp else "python_host",
        "lanes": args.lanes,
        "virtual_secs": args.virtual_secs,
        "n_devices": tpu["n_devices"],
        "seeds_per_sec_per_chip": round(
            tpu["seeds_per_sec"] / tpu["n_devices"], 2
        ),
        "tpu_wall_s": round(tpu["wall_s"], 3),
        "tpu_events_per_sec": round(tpu["events_per_sec"], 1),
        "tpu_step_ms": round(tpu["step_ms"], 3),
        "tpu_steps_run": tpu["steps_run"],
        "cpu_baseline_seeds_per_sec": round(cpu["seeds_per_sec"], 3),
        "cpu_baseline_events_per_sec": round(cpu["events_per_sec"], 1),
        "cpp_baseline_seeds_per_sec": (
            round(cpp["seeds_per_sec"], 2) if cpp else None
        ),
        "cpp_baseline_events_per_sec": (
            round(cpp["events_per_sec"], 1) if cpp else None
        ),
        # the denominator's own error bar (median of 5 pinned runs): the
        # headline ratio is only as stable as this spread
        "cpp_baseline_spread_pct": cpp.get("spread_pct") if cpp else None,
        "cpp_baseline_min_max": (
            [cpp.get("seeds_per_sec_min"), cpp.get("seeds_per_sec_max")]
            if cpp else None
        ),
        "vs_python_host": round(tpu["seeds_per_sec"] / cpu["seeds_per_sec"], 2),
        "violations": tpu["summary"]["violations"],
        "overflow": tpu["summary"]["total_overflow"],
        "log_saturated_lanes": tpu["summary"].get("log_saturated_lanes", 0),
        # second device protocol (replicated-KV linearizability, partitions on)
        "kv_seeds_per_sec": round(kv["seeds_per_sec"], 2),
        "kv_lanes": args.lanes,
        "kv_violations": kv["summary"]["violations"],
        "kv_mean_acked_ops": round(kv["summary"].get("mean_acked_ops", 0.0), 2),
        "kv_history_wrapped_lanes": kv["summary"].get("history_wrapped_lanes", 0),
        "kv_overflow": kv["summary"]["total_overflow"],
        # what fraction of acked ops the EXACT (Wing-Gong) oracle checked
        # on a 128-lane sample (the device oracle covers the rest; r4's
        # 24-op ring wrapped on >99% of lanes and left most evidence to
        # watermarks alone — the r5 horizon-sized ring closes that)
        "kv_exact_check": kv["summary"].get("exact_check"),
        # third device protocol (2PC atomicity, full chaos battery)
        "twopc_seeds_per_sec": round(twopc["seeds_per_sec"], 2),
        "twopc_lanes": args.lanes,
        "twopc_violations": twopc["summary"]["violations"],
        "twopc_overflow": twopc["summary"]["total_overflow"],
        "twopc_mean_decided_txns": round(
            twopc["summary"].get("mean_decided_txns", 0.0), 1
        ),
        # fourth device protocol (Paxos agreement, full chaos battery)
        "paxos_seeds_per_sec": round(paxos["seeds_per_sec"], 2),
        "paxos_lanes": args.lanes // 4,
        "paxos_violations": paxos["summary"]["violations"],
        "paxos_overflow": paxos["summary"]["total_overflow"],
        "paxos_all_decided_lanes": paxos["summary"].get(
            "all_decided_lanes", 0
        ),
        # fifth device protocol (chain replication, loss + crash chaos)
        "chain_seeds_per_sec": round(chain["seeds_per_sec"], 2),
        "chain_lanes": args.lanes // 4,
        "chain_violations": chain["summary"]["violations"],
        "chain_overflow": chain["summary"]["total_overflow"],
        "chain_mean_committed_vers": round(
            chain["summary"].get("mean_committed_vers", 0.0), 1
        ),
        # heavy-tail buggify A/B (events explored with/without the tail)
        "buggify_ab": buggify,
        **breakdown,
        **roofline,
        # time-to-first-bug (the metric's other half): wall-clock from a
        # cold runtime to a confirmed violating seed and to a finished
        # ReproBundle, on the in-tree planted-bug configs
        "ttfb": ttfb,
        "ttfb_raft_restamp_s": (
            ttfb.get("raft_restamp", {}).get("wall_to_first_violation_s")
            if isinstance(ttfb, dict) else None
        ),
        "ttfb_raft_restamp_bundle_s": (
            ttfb.get("raft_restamp", {}).get("wall_to_bundle_s")
            if isinstance(ttfb, dict) else None
        ),
        "ttfb_chain_straggler_s": (
            ttfb.get("chain_straggler", {}).get("wall_to_first_violation_s")
            if isinstance(ttfb, dict) else None
        ),
        "ttfb_chain_straggler_bundle_s": (
            ttfb.get("chain_straggler", {}).get("wall_to_bundle_s")
            if isinstance(ttfb, dict) else None
        ),
        # coverage-guided explorer vs the uniform sweep (same lane budget;
        # dispatch_advantage >= 0 is the acceptance bar — generation 0 IS
        # the uniform sweep's first chunk)
        "explore": explore,
        "explore_raft_restamp_dispatch_advantage": (
            explore.get("raft_restamp", {}).get("dispatch_advantage")
            if isinstance(explore, dict) else None
        ),
        "explore_raft_restamp_coverage_gain_pct": (
            explore.get("raft_restamp", {}).get("coverage_gain_pct")
            if isinstance(explore, dict) else None
        ),
        "explore_chain_straggler_dispatch_advantage": (
            explore.get("chain_straggler", {}).get("dispatch_advantage")
            if isinstance(explore, dict) else None
        ),
        "explore_chain_straggler_coverage_gain_pct": (
            explore.get("chain_straggler", {}).get("coverage_gain_pct")
            if isinstance(explore, dict) else None
        ),
        # default-vs-tuned seeds/s per workload (r13): the measured
        # autotuner's win carried as a number — Tier-A dispatch knobs
        # only, per-seed results bit-identical across the A/B
        "tuned": tuned,
        # host-vs-device generation loop (r19): the same search both
        # ways — device budget is <= 1 blocking sync per generation
        # (one per window) vs the host loop's decode every generation,
        # report fingerprints bit-identical
        "generations_per_s": devloop,
        "devloop_dispatch_ratio": (
            devloop.get("dispatch_ratio")
            if isinstance(devloop, dict) else None
        ),
        "devloop_device_syncs_per_gen": (
            devloop.get("device", {}).get("syncs_per_gen")
            if isinstance(devloop, dict) else None
        ),
        # telemetry span-site cost: wrapped vs bare dispatch loop on the
        # smoke workload (<2% pinned by tests/test_telemetry.py)
        "telemetry_overhead": telemetry_overhead,
        "telemetry_overhead_pct": telemetry_overhead["overhead_pct"],
        "backend": tpu["backend"],
        "notes": (
            "r6 changes, engine + measurement: (1) buffer donation "
            "end-to-end — every sweep segment (run/_run, traced replay, "
            "triage ddmin lanes) donates its carry state, so segment "
            "boundaries reuse HBM in place instead of allocating a fresh "
            "state pytree per dispatch (bit-identity proven by tests). "
            "(2) Double-buffered pipelines: run_batch dispatches chunk "
            "k+1 before decoding chunk k's violation scalars; the triage "
            "shrinker overlaps ddmin generation chunks the same way "
            "(legal: candidates are independent). Host-side decode (incl. "
            "the kv exact oracle) now overlaps device time. (3) r5 kit "
            "ported to the trailing workloads: twopc's lax.switch x "
            "all-branches + dual-body fuse_two_handlers wrapper replaced "
            "by a hand-fused masked on_event (one state build, ONE "
            "outcome-ring pass instead of three; trajectories "
            "bit-identical to r5); kv's oracle folds its three ring "
            "comparisons into one reduction. kv/twopc now sweep FULL "
            "lanes (kv_lanes/twopc_lanes report it): the r5 //4 sizing "
            "left the chip idle on exactly the trailing workloads — "
            "twopc runs ~1.4k steps/sweep, raft-like, so its gap was "
            "utilization, not step cost. (4) roofline_rows: per-workload "
            "bytes/step from compiled.memory_analysis() (arg + out + "
            "2*temp) with ONE +-20% honesty interval (bracket 1.44x, vs "
            "the r5 lo/hi pair 3.7x apart). (5) ttfb_*: time-to-first-"
            "bug measured for the first time — cold-runtime wall to a "
            "confirmed violating seed and to a shrunk ReproBundle on two "
            "planted-bug configs. Headline keeps the zero-drop "
            "discipline (overflow==0); C++ denominator unchanged "
            "(median-of-5 pinned, spread reported). r13: measured "
            "autotune (madsim_tpu.tune) — `tuned` carries the "
            "default-vs-tuned A/B per workload (Tier-A dispatch knobs; "
            "per-seed rows bit-identical across the A/B), `kernel_rows` "
            "the per-fused-kernel HBM attribution of the headline raft "
            "step, and every timing loop runs the shared "
            "madsim_tpu.measure discipline."
        ),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
