"""Headline benchmark: seeds/sec fuzzing 5-node Raft (BASELINE.json metric).

Compares the TPU batched engine (thousands of seed lanes per jitted step)
against the reference execution model: one full simulation per seed on the
host executor (the thread-per-seed CPU baseline,
reference runtime/builder.rs:118-136).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "seeds/s", "vs_baseline": N, ...}
"""

from __future__ import annotations

import argparse
import json
import time


def _timed_median_of_3(sim, lanes: int, max_steps: int):
    """Warm-compile, then time 3 fresh-seed reps and take the median wall.

    The tunnel TPU is shared — external contention has been observed to
    halve throughput for stretches, and one transient tunnel hiccup
    produced a physically impossible 53 ms rep. The median ignores a
    single outlier in EITHER direction."""
    import jax.numpy as jnp

    state = sim.run(jnp.arange(lanes), max_steps=max_steps)  # compile + warm
    state.clock.block_until_ready()
    walls = []
    for rep in range(1, 4):
        t0 = time.perf_counter()
        state = sim.run(
            jnp.arange(rep * lanes, (rep + 1) * lanes), max_steps=max_steps
        )
        state.clock.block_until_ready()
        walls.append(time.perf_counter() - t0)
    return sorted(walls)[1], state


def bench_tpu(lanes: int, virtual_secs: float, client_rate: float) -> dict:
    import jax
    import jax.numpy as jnp

    from madsim_tpu.tpu import BatchedSim, SimConfig, make_raft_spec, summarize

    spec = make_raft_spec(n_nodes=5, client_rate=client_rate)
    cfg = SimConfig(
        horizon_us=int(virtual_secs * 1e6),
        # 4 slots per origin region: r02's 64 (2/region) overflowed 894
        # messages over the sweep — unaccounted loss outside loss_rate;
        # headline config must drop NOTHING the network didn't roll to drop
        msg_capacity=128,
        loss_rate=0.10,
        crash_interval_lo_us=500_000,
        crash_interval_hi_us=3_000_000,
        restart_delay_lo_us=300_000,
        restart_delay_hi_us=2_000_000,
        # partition chaos on: random bipartitions every 0.3-1.5s, healing
        # after 0.5-2s (the host baseline runs the same partition schedule
        # rate via fuzz_one_seed(partitions=True))
        partition_interval_lo_us=300_000,
        partition_interval_hi_us=1_500_000,
        partition_heal_lo_us=500_000,
        partition_heal_hi_us=2_000_000,
    )
    sim = BatchedSim(spec, cfg)
    max_steps = int(virtual_secs * 600) + 2000  # generous event budget
    wall, state = _timed_median_of_3(sim, lanes, max_steps)
    s = summarize(state, spec)
    return {
        "wall_s": wall,
        "seeds_per_sec": lanes / wall,
        "events_per_sec": s["total_events"] / wall,
        "summary": s,
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
    }


def bench_kv(lanes: int, virtual_secs: float) -> dict:
    """Second device protocol: replicated-KV linearizability under
    partitions (BASELINE config #4 / SURVEY §7 step 5). Client histories
    recorded per lane; the invariant is real-time revision monotonicity."""
    import jax.numpy as jnp

    from madsim_tpu.tpu import BatchedSim, summarize
    from madsim_tpu.tpu.kv import kv_workload, make_kv_spec

    wl = kv_workload(virtual_secs=virtual_secs)
    sim = BatchedSim(wl.spec, wl.config)
    max_steps = int(virtual_secs * 1200) + 2000

    wall, state = _timed_median_of_3(sim, lanes, max_steps)
    s = summarize(state, wl.spec)
    return {
        "wall_s": wall,
        "seeds_per_sec": lanes / wall,
        "summary": s,
    }


def bench_twopc(lanes: int, virtual_secs: float) -> dict:
    """Third device protocol: Two-Phase Commit atomicity under the full
    chaos battery (loss + coordinator crashes + partitions)."""
    import jax.numpy as jnp

    from madsim_tpu.tpu import BatchedSim, SimConfig, make_twopc_spec, summarize

    sim = BatchedSim(
        make_twopc_spec(5),
        SimConfig(
            horizon_us=int(virtual_secs * 1e6),
            # 50 candidate positions (N * max_out + N * max_out_msg) x 2+
            # slots: overflow must be 0 — nothing dropped outside loss_rate
            msg_capacity=128,
            loss_rate=0.1,
            crash_interval_lo_us=400_000,
            crash_interval_hi_us=2_000_000,
            restart_delay_lo_us=200_000,
            restart_delay_hi_us=1_000_000,
            partition_interval_lo_us=400_000,
            partition_interval_hi_us=1_500_000,
            partition_heal_lo_us=300_000,
            partition_heal_hi_us=1_200_000,
        ),
    )
    max_steps = int(virtual_secs * 1600) + 2000

    wall, state = _timed_median_of_3(sim, lanes, max_steps)
    return {
        "wall_s": wall,
        "seeds_per_sec": lanes / wall,
        "summary": summarize(state, sim.spec),
    }


def bench_cpp_baseline(n_seeds: int, virtual_secs: float, client_rate: float) -> dict:
    """The HONEST CPU denominator: a compiled thread-per-seed DES fuzzer
    (native/raft_bench.cpp) running the same protocol + chaos + invariant
    checks as the device spec, single-core — what the reference's compiled
    Rust executor model achieves per core on this workload. Compiled on
    demand with g++ -O2; returns None when no C++ toolchain exists.
    """
    import pathlib
    import shutil
    import subprocess

    src = pathlib.Path(__file__).parent / "madsim_tpu" / "native" / "raft_bench.cpp"
    out = pathlib.Path(__file__).parent / "build" / "raft_bench"
    gxx = shutil.which("g++") or shutil.which("clang++")
    if gxx is None or not src.exists():
        return None
    if not out.exists() or out.stat().st_mtime < src.stat().st_mtime:
        out.parent.mkdir(exist_ok=True)
        r = subprocess.run(
            [gxx, "-O2", "-std=c++17", "-o", str(out), str(src)],
            capture_output=True, text=True,
        )
        if r.returncode != 0:
            return None
    rows = []
    for _ in range(3):  # median of 3, same rep scheme as every other side
        try:
            r = subprocess.run(
                [str(out), str(n_seeds), str(virtual_secs), str(client_rate), "0.1"],
                capture_output=True, text=True, timeout=600,
            )
            if r.returncode != 0:
                break
            rows.append(json.loads(r.stdout.strip().splitlines()[-1]))
        except (subprocess.TimeoutExpired, ValueError, IndexError):
            # keep any completed reps; missing-toolchain/compile-failure paths
            # degrade to the python_host denominator — never kill the bench
            break
    if not rows:
        return None
    return sorted(rows, key=lambda x: x["seeds_per_sec"])[(len(rows) - 1) // 2]


def bench_cpu_baseline(n_seeds: int, virtual_secs: float, client_rate: float) -> dict:
    from madsim_tpu.workloads.raft_host import fuzz_one_seed

    # warm one seed (imports, code paths)
    fuzz_one_seed(
        999_983, virtual_secs=virtual_secs, client_rate=client_rate, partitions=True
    )
    rows = []
    for rep in range(3):  # median of 3, same rep scheme as every other side
        t0 = time.perf_counter()
        events = 0
        for seed in range(rep * n_seeds, (rep + 1) * n_seeds):
            r = fuzz_one_seed(
                seed, virtual_secs=virtual_secs, client_rate=client_rate,
                partitions=True,
            )
            events += r["events"]
        wall = time.perf_counter() - t0
        rows.append({
            "wall_s": wall,
            "seeds_per_sec": n_seeds / wall,
            "events_per_sec": events / wall,
        })
    return sorted(rows, key=lambda x: x["seeds_per_sec"])[1]


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--lanes", type=int, default=32768)
    parser.add_argument("--virtual-secs", type=float, default=10.0)
    parser.add_argument("--cpu-seeds", type=int, default=16)
    # client_rate sized so the TPU spec's fixed-capacity log does NOT
    # saturate within the horizon (10s x 0.1/heartbeat ~ 20 appends < 24
    # capacity) — both backends then run the same protocol work end to end
    parser.add_argument("--client-rate", type=float, default=0.1)
    args = parser.parse_args()

    cpu = bench_cpu_baseline(args.cpu_seeds, args.virtual_secs, args.client_rate)
    cpp = bench_cpp_baseline(
        max(args.cpu_seeds * 16, 256), args.virtual_secs, args.client_rate
    )
    tpu = bench_tpu(args.lanes, args.virtual_secs, args.client_rate)
    kv = bench_kv(args.lanes // 4, args.virtual_secs)
    twopc = bench_twopc(args.lanes // 4, args.virtual_secs)

    # vs_baseline is computed against the STRONGEST CPU execution available:
    # the compiled C++ thread-per-seed DES (the reference's execution model)
    # when a toolchain exists, else the Python host runtime. Both
    # denominators are reported; the C++ one is single-core (the reference
    # sweeps seeds thread-per-core, so per-core is the honest unit).
    strongest = max(
        cpu["seeds_per_sec"], cpp["seeds_per_sec"] if cpp else 0.0
    )
    result = {
        "metric": "raft5_fuzz_seeds_per_sec",
        "value": round(tpu["seeds_per_sec"], 2),
        "unit": "seeds/s",
        "vs_baseline": round(tpu["seeds_per_sec"] / strongest, 2),
        "baseline_kind": "cpp_compiled_single_core" if cpp else "python_host",
        "lanes": args.lanes,
        "virtual_secs": args.virtual_secs,
        "tpu_wall_s": round(tpu["wall_s"], 3),
        "tpu_events_per_sec": round(tpu["events_per_sec"], 1),
        "cpu_baseline_seeds_per_sec": round(cpu["seeds_per_sec"], 3),
        "cpu_baseline_events_per_sec": round(cpu["events_per_sec"], 1),
        "cpp_baseline_seeds_per_sec": (
            round(cpp["seeds_per_sec"], 2) if cpp else None
        ),
        "cpp_baseline_events_per_sec": (
            round(cpp["events_per_sec"], 1) if cpp else None
        ),
        "vs_python_host": round(tpu["seeds_per_sec"] / cpu["seeds_per_sec"], 2),
        "violations": tpu["summary"]["violations"],
        "overflow": tpu["summary"]["total_overflow"],
        "log_saturated_lanes": tpu["summary"].get("log_saturated_lanes", 0),
        # second device protocol (replicated-KV linearizability, partitions on)
        "kv_seeds_per_sec": round(kv["seeds_per_sec"], 2),
        "kv_lanes": args.lanes // 4,
        "kv_violations": kv["summary"]["violations"],
        "kv_mean_acked_ops": round(kv["summary"].get("mean_acked_ops", 0.0), 2),
        "kv_history_wrapped_lanes": kv["summary"].get("history_wrapped_lanes", 0),
        # third device protocol (2PC atomicity, full chaos battery)
        "twopc_seeds_per_sec": round(twopc["seeds_per_sec"], 2),
        "twopc_lanes": args.lanes // 4,
        "twopc_violations": twopc["summary"]["violations"],
        "twopc_overflow": twopc["summary"]["total_overflow"],
        "twopc_mean_decided_txns": round(
            twopc["summary"].get("mean_decided_txns", 0.0), 1
        ),
        "backend": tpu["backend"],
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
