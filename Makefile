# Developer entry points (the reference's Makefile:1-24 analog: its targets
# build+test every crate twice, normal and --cfg madsim; ours split by test
# tier and mode instead — the sim/real duality is exercised inside the
# suite via MADSIM_NET_BACKEND / real-mode tests).

PY ?= python

.PHONY: test deep test-all lint analyze check chaos-smoke triage-smoke explore-smoke campaign-smoke refill-smoke devloop-smoke multichip-smoke telemetry-smoke explain-smoke oracle-smoke reconfig-smoke durability-smoke speclang-smoke tune tune-smoke regression real native bench bench-smoke campaign-bench compaction-ab ttfb explore-bench dryrun demo clean

test:            ## fast tier (< ~3.5 min; what CI runs per-commit)
	$(PY) -m pytest tests/ -q

lint:            ## source-level invariant lints: entropy, mirror, both-faces, layout, markers (fast)
	$(PY) -m madsim_tpu.analysis

analyze:         ## full static verifier: lints + jaxpr + range certificates over all five workloads
	$(PY) -m madsim_tpu.analysis --all

check: lint analyze  ## the fast pre-commit gate: every static rule, no pytest

deep:            ## deep device sweeps (~10 min; CI nightly)
	$(PY) -m pytest tests/ -q -m deep

chaos-smoke:     ## fast nemesis smoke: 64-lane fault plans on both backends
	$(PY) -m pytest tests/ -q -m "chaos and not slow"

triage-smoke:    ## tiny seeded shrink of a planted raft bug + bundle replay
	$(PY) -m pytest tests/test_triage.py -q -m "chaos and not slow"

explore-smoke:   ## coverage-guided search smoke: monotone coverage + meta-seed determinism (CPU)
	$(PY) -m pytest tests/test_explore.py -q -m "chaos and not slow"

campaign-smoke:  ## mini campaign: kill -> resume fingerprint match, dedup, merge/cmin, regression replay
	$(PY) -m madsim_tpu.analysis --quiet --rule range --workload raft
	$(PY) -m pytest tests/test_campaign.py -q -m "chaos and not slow"

refill-smoke:    ## continuous batching: >=90% occupancy on a 10x horizon-spread mix, dispatch budget, bit-identity (<60s)
	$(PY) benches/refill_smoke.py

devloop-smoke:   ## device-resident search (r19): host/device fingerprint bit-identity, <=1 sync per window, dispatch budget (<60s)
	$(PY) benches/devloop_smoke.py

multichip-smoke: ## multi-chip fleet on the virtual 8-device mesh: refill bit-identity across device counts, >=0.9 per-device occupancy, >=6x lane-step scaling, federation fingerprint (<60s warm)
	$(PY) -m pytest tests/test_multichip.py -q -m "chaos and not slow"

telemetry-smoke: ## telemetry observe-only contract: on/off bit-identity (fingerprint + golden digest), schema round-trip, Perfetto/format_trace parity, repro --perfetto, serve status atomicity, <2% span overhead (<2min warm; runs the WHOLE file incl. slow-marked tests — the tier-1 budget keeps only the fast ones)
	$(PY) -m pytest tests/test_telemetry.py -q -m "not deep"

explain-smoke:   ## causal explainability end to end: the <60s-warm bench gate (planted raft re-stamp -> lineage slice names the re-stamp APPEND delivery chain -> cross-witness skeleton; lineage carry <= 15% budget), then the WHOLE causal suite incl. the slow-marked shrink/anatomy tests the tier-1 wall budget keeps out
	$(PY) benches/explain_smoke.py
	$(PY) -m pytest tests/test_causal.py -q -m "not deep"

oracle-smoke:    ## <60s CPU: the differential oracle both ways — a small raft chaos sweep replays schedule-matched on the host twin with zero divergences, then the planted reorder off-by-one fires, localizes to the reorder-window draw, and ddmin-shrinks to the reorder clause (never vacuously green), then the oracle suite
	$(PY) benches/oracle_smoke.py
	$(PY) -m pytest tests/test_oracle.py -q

reconfig-smoke:  ## <60s CPU: membership as a fault axis end to end — the planted kafka-family stale-ISR bug under a reconfig-ONLY plan is found by the explorer, ddmin-shrinks to reconfig occurrence atoms, campaign-dedups to ONE BugRecord, and the cross-witness anatomy names the rejoined replica's FETCH delivery; then the isr/lease spec suites
	$(PY) benches/reconfig_smoke.py
	$(PY) -m pytest tests/test_tpu_isr.py tests/test_tpu_lease.py -q -m "not slow"

durability-smoke: ## <80s CPU: durability as a fault axis end to end — the planted ack-before-fsync WAL bug under a disk-ONLY plan is found by the explorer, ddmin-shrinks to disk occurrence atoms, campaign-dedups to ONE BugRecord, and the cross-witness anatomy names the ACK delivery fsync never covered; then the wal/fs spec suites
	$(PY) benches/durability_smoke.py
	$(PY) -m pytest tests/test_tpu_wal.py tests/test_fs_durability.py -q -m "not slow"

speclang-smoke:  ## <60s CPU warm: single-source specs end to end — regenerate and diff the emitted modules against the checked-in files, verifier+certifier gate on the speclang-native backup protocol, golden-digest identity for the twopc re-derivation, planted stale-read bug fires/shrinks to its message axis/replays from the ReproBundle on both faces; then the speclang spec suite
	$(PY) -m madsim_tpu.speclang emit --check
	$(PY) -m madsim_tpu.analysis --quiet --rule range --workload backup
	$(PY) benches/speclang_smoke.py
	$(PY) -m pytest tests/test_speclang.py -q

tune:            ## measured autotune over every workload's throughput knobs; winners cached per (device_kind, workload, config, lane bucket) and consumed via tuning="auto" (docs/tuning.md)
	$(PY) -m madsim_tpu.tune --workload all --virtual-secs 10 --lanes 32768

tune-smoke:      ## <60s CPU: one Tier-A coordinate pass on the spread mix (tuner >= hand-pinned default, never a regression), tuned-vs-default bit-identity, Tier-B gate rejects a planted dropping pool config
	$(PY) benches/tune_smoke.py

regression:      ## replay the regression corpus of deduped bug bundles green
	$(PY) -m madsim_tpu.campaign regress $(if $(REGRESSION_DIR),--dir $(REGRESSION_DIR),)

test-all: test deep

real:            ## real-socket mode across all three net backends
	$(PY) -m pytest tests/test_real_mode.py tests/test_unix.py -q

native:          ## (re)build the C++ executor core in place
	$(PY) setup_native.py build_ext --inplace

bench:           ## the headline JSON line (runs on the live jax backend)
	$(PY) bench.py

bench-smoke:     ## <60s/workload micro-bench: completion + dispatch + layout budgets, never wall-clock
	$(PY) -m madsim_tpu.analysis --quiet --rule range --workload raft
	$(PY) benches/bench_smoke.py

compaction-ab:   ## r8 layout A/B: serial-vs-donated + packed-vs-unpacked bit-identity (<60s, structural)
	$(PY) benches/compaction_ab.py

ttfb:            ## time-to-first-bug: cold-runtime wall to violation + ReproBundle on planted bugs
	$(PY) benches/ttfb.py

explore-bench:   ## explorer vs uniform sweep: coverage/dispatch + first-bug dispatches on planted bugs
	$(PY) benches/explore_bench.py

campaign-bench:  ## campaign-layer overheads: checkpoint/resume wall, merge+cmin throughput (<60s, structural)
	$(PY) benches/campaign_bench.py

dryrun:          ## multi-chip sharding dry run on a virtual 8-device mesh
	cd /tmp && $(PY) $(CURDIR)/__graft_entry__.py

demo:            ## the fuzz workflow end to end (plant bug, sweep, trace)
	$(PY) examples/fuzz_demo.py

clean:
	rm -rf build .pytest_cache madsim_tpu/native/*.so
	find . -name __pycache__ -type d -exec rm -rf {} +
